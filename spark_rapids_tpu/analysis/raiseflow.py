"""tpufsan — inter-procedural exception-flow & resource-release lint.

Ref: the reference plugin's resilience rests on disciplined error
propagation across the JNI/shuffle boundary (typed fetch failures that
the stage-retry scheduler dispatches on, RMM retry/OOM unwinding that
releases every reservation it holds).  This pass proves the same
discipline statically for our port, the third instance of the
static-pass + runtime-witness pattern tpucsan (locks) and tmsan
(device memory) established:

  * per function, the set of TYPED errors it can raise — seeded from
    explicit ``raise`` sites, propagated over the tpucsan-resolved call
    graph (typed edges only; the CHA fallback is reachability-grade,
    not propagation-grade), narrowed by ``except`` clauses;
  * four repo rules over that raise graph:

      TPU-R011  overbroad/bare ``except`` that swallows a typed engine
                error without re-raising it or routing it through a
                sanctioned sink (postmortem / black-box recording, the
                background-error router, a relay that hands the caught
                exception onward);
      TPU-R012  a resource acquired on a path where a raising
                successor can skip its release — the release
                obligation is declared per acquire API
                (``_OBLIGATIONS``); ``with``, ``try/finally`` and
                ownership-transfer idioms are recognized;
      TPU-R013  an untyped operational exception (RuntimeError,
                TimeoutError, OSError family) escaping a public seam
                whose callers dispatch on the typed taxonomy — scoped
                to raises originating inside the seam's own subsystem
                so a deep utility ValueError is not the seam's debt;
      TPU-R014  a socket created or accepted on a thread-root-reachable
                path with no explicit deadline (a hung peer must never
                pin a daemon thread forever).

The computed raise graph doubles as the *test plan*: ``tools lint
--raise-graph`` dumps per-seam raise sets plus the injection plan, and
``devtools/run_lint.py --faults`` replays the golden corpus once per
statically-reachable (seam, typed-error) pair with that fault
monkeypatch-injected, asserting typed propagation, balanced books and
a postmortem bundle — the same artifact hand-off the lock witness uses
against the tpucsan lock-order artifact.

Suppression: ``# tpulint: allow[TPU-R01x] reason`` on the flagged line,
same as every repo rule.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from .diagnostics import Diagnostic, register_rule

R011 = register_rule(
    "TPU-R011", "error", "broad except swallows a typed engine error",
    "An overbroad or typed `except` consumes an engine error from the "
    "typed taxonomy without re-raising it or routing it through a "
    "sanctioned sink (postmortem/black-box recording, the background-"
    "error router, or a relay that passes the exception onward). "
    "Callers dispatching on the taxonomy never see the failure.")
R012 = register_rule(
    "TPU-R012", "error", "raising path can skip a resource release",
    "A resource with a declared release obligation (admission ticket, "
    "tracer span, spill registration, pooled session, socket) is "
    "acquired where a raising successor can unwind past the release. "
    "Use `with`, a `try/finally`, or transfer ownership explicitly.")
R013 = register_rule(
    "TPU-R013", "error", "untyped exception escapes a public seam",
    "A public seam whose callers dispatch on the typed error taxonomy "
    "can leak an untyped operational exception (RuntimeError, "
    "TimeoutError, OSError family) raised inside the seam's own "
    "subsystem. Type the failure so retry/backpressure policy can act "
    "on it.")
R014 = register_rule(
    "TPU-R014", "error", "socket on a thread root has no deadline",
    "A socket created, connected or accepted on a path reachable from "
    "a daemon-thread root carries no explicit timeout: a hung peer "
    "pins the thread forever. Pass timeout= at creation or call "
    "settimeout() before blocking I/O.")


# ---------------------------------------------------------------------------
# taxonomy: builtin exception hierarchy + package-defined classes
# ---------------------------------------------------------------------------

# the slice of the builtin hierarchy the repo actually raises/catches
_BUILTIN_EXC_PARENTS: Dict[str, Tuple[str, ...]] = {
    "BaseException": (),
    "Exception": ("BaseException",),
    "GeneratorExit": ("BaseException",),
    "KeyboardInterrupt": ("BaseException",),
    "SystemExit": ("BaseException",),
    "StopIteration": ("Exception",),
    "StopAsyncIteration": ("Exception",),
    "ArithmeticError": ("Exception",),
    "ZeroDivisionError": ("ArithmeticError",),
    "OverflowError": ("ArithmeticError",),
    "AssertionError": ("Exception",),
    "AttributeError": ("Exception",),
    "ImportError": ("Exception",),
    "ModuleNotFoundError": ("ImportError",),
    "LookupError": ("Exception",),
    "KeyError": ("LookupError",),
    "IndexError": ("LookupError",),
    "MemoryError": ("Exception",),
    "NameError": ("Exception",),
    "NotImplementedError": ("RuntimeError",),
    "RecursionError": ("RuntimeError",),
    "RuntimeError": ("Exception",),
    "OSError": ("Exception",),
    "IOError": ("OSError",),
    "FileNotFoundError": ("OSError",),
    "FileExistsError": ("OSError",),
    "PermissionError": ("OSError",),
    "ConnectionError": ("OSError",),
    "ConnectionResetError": ("ConnectionError",),
    "ConnectionRefusedError": ("ConnectionError",),
    "ConnectionAbortedError": ("ConnectionError",),
    "BrokenPipeError": ("ConnectionError",),
    "TimeoutError": ("OSError",),
    "InterruptedError": ("OSError",),
    "TypeError": ("Exception",),
    "ValueError": ("Exception",),
    "UnicodeDecodeError": ("ValueError",),
    "UnicodeEncodeError": ("ValueError",),
    # dotted builtins the transport/codec layers touch
    "socket.timeout": ("TimeoutError",),
    "socket.error": ("OSError",),
    "struct.error": ("Exception",),
    "json.JSONDecodeError": ("ValueError",),
    "queue.Empty": ("Exception",),
    "queue.Full": ("Exception",),
    "pickle.PicklingError": ("Exception",),
}

# Exception-typed catches do NOT consume these
_NOT_UNDER_EXCEPTION = {"GeneratorExit", "KeyboardInterrupt",
                        "SystemExit", "BaseException"}

# R013: the untyped *operational* failures callers would have to
# dispatch on blind.  Programming errors (ValueError/TypeError/KeyError
# ...) stay out: they indicate caller bugs, not runtime conditions a
# retry/backpressure policy acts on.
_UNTYPED_OPERATIONAL = {
    "RuntimeError", "TimeoutError", "OSError", "IOError",
    "ConnectionError", "ConnectionResetError", "BrokenPipeError",
    "socket.timeout", "socket.error", "Exception", "BaseException",
}

# dynamic raise whose class the pass cannot resolve (``raise f(x)``)
_DYNAMIC = "<dynamic>"

_PKG_PREFIX = "spark_rapids_tpu/"


def _path_under(relpath: str, prefix: str) -> bool:
    """Does ``relpath`` live under ``prefix``?  Tolerates relpaths that
    carry the package directory (spark_rapids_tpu/api/...) against
    package-relative prefixes (api/)."""
    if relpath.startswith(_PKG_PREFIX):
        relpath = relpath[len(_PKG_PREFIX):]
    if prefix.startswith(_PKG_PREFIX):
        prefix = prefix[len(_PKG_PREFIX):]
    return relpath == prefix or relpath.startswith(prefix)


def _package_exceptions(sources: Dict[str, str]) -> Dict[str, Dict]:
    """{class name: {"bases": (...), "relpath": ..., "lineno": ...}}
    for every exception class defined in the package (transitively
    rooted in a builtin exception)."""
    classes: Dict[str, Dict] = {}
    for relpath, src in sources.items():
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            bases = []
            for b in node.bases:
                if isinstance(b, ast.Name):
                    bases.append(b.id)
                elif isinstance(b, ast.Attribute):
                    bases.append(b.attr)
            classes.setdefault(node.name, {
                "bases": tuple(bases), "relpath": relpath,
                "lineno": node.lineno})
    # fixpoint: a class is an exception iff some base is
    exc: Dict[str, Dict] = {}
    changed = True
    while changed:
        changed = False
        for name, info in classes.items():
            if name in exc:
                continue
            if any(b in _BUILTIN_EXC_PARENTS or b in exc
                   for b in info["bases"]):
                exc[name] = info
                changed = True
    return exc


class Taxonomy:
    """Subclass lattice over builtin + package exception names."""

    def __init__(self, package_exc: Dict[str, Dict]):
        self.package_exc = package_exc
        self._parents: Dict[str, Tuple[str, ...]] = dict(
            _BUILTIN_EXC_PARENTS)
        for name, info in package_exc.items():
            self._parents[name] = tuple(
                b for b in info["bases"]
                if b in _BUILTIN_EXC_PARENTS or b in package_exc)

    def is_typed(self, name: str) -> bool:
        return name in self.package_exc

    def ancestors(self, name: str) -> Set[str]:
        out: Set[str] = set()
        work = [name]
        while work:
            cur = work.pop()
            for p in self._parents.get(cur, ()):
                if p not in out:
                    out.add(p)
                    work.append(p)
        return out

    def catches(self, caught: str, raised: str) -> bool:
        """Would ``except caught`` consume ``raise raised``?"""
        if raised == _DYNAMIC:
            return caught in ("*", "BaseException", "Exception")
        if caught == "*" or caught == "BaseException":
            return True
        if caught == "Exception":
            return raised not in _NOT_UNDER_EXCEPTION
        return raised == caught or caught in self.ancestors(raised)

    def is_broad(self, types: Tuple[str, ...]) -> bool:
        return any(t in ("*", "Exception", "BaseException")
                   for t in types)


# ---------------------------------------------------------------------------
# seams and obligations
# ---------------------------------------------------------------------------

# (label, relpath suffix, scope suffix, subsystem prefixes whose
#  untyped raises are the seam's R013 debt)
SEAMS: Tuple[Tuple[str, str, str, Tuple[str, ...]], ...] = (
    ("main-query", "api/session.py", "TpuSession.execute", ("api/",)),
    ("serving-client", "api/pool.py", "SessionPool.run", ("api/",)),
    ("pool-borrow", "api/pool.py", "SessionPool._borrow", ("api/",)),
    ("pool-drain", "api/pool.py", "SessionPool.drain", ("api/",)),
    ("shuffle-fetcher", "shuffle/transport.py",
     "AsyncBlockFetcher.blocks", ("shuffle/",)),
    ("block-server", "shuffle/transport.py", "ShuffleServer._serve_one",
     ("shuffle/",)),
    ("heartbeat-loop", "shuffle/heartbeat.py", "HeartbeatEndpoint._run",
     ("shuffle/",)),
    ("metrics-http", "obs/health.py", "do_GET", ("obs/health.py",)),
)

# a seam whose workload is a caller-supplied callable executes another
# seam's body at runtime even though the call is statically invisible:
# SessionPool.run(fn) invokes fn(session) which drives
# TpuSession.execute in every real caller — its injection plan
# inherits the delegate's
_SEAM_DELEGATES: Dict[str, Tuple[str, ...]] = {
    "serving-client": ("main-query",),
}

# release obligations: acquire fid suffix -> (label, release method
# names).  The release call must be guaranteed (with / finally) or
# ownership must leave the function (returned, yielded, stored on
# self/module state, or handed to another call).
_OBLIGATIONS: Tuple[Tuple[str, str, Tuple[str, ...]], ...] = (
    ("admission ticket",
     "memory/admission.py::AdmissionController.admit", ("release",)),
    ("tracer span",
     "obs/tracer.py::QueryTrace.start", ("end", "finalize")),
    ("spill registration",
     "memory/spill.py::SpillCatalog.register",
     ("unregister", "close")),
    ("pooled session",
     "api/pool.py::SessionPool._borrow", ("_return", "close")),
    # NOT an obligation: TpuShuffleManager.write_map_output — map
    # outputs are stage-scoped by design (release_plan_shuffles runs
    # in the session's except-BaseException arm); the books checks in
    # the --shuffle/--serve/--faults gates witness that at runtime.
)

# handler callees that count as sanctioned sinks for TPU-R011: failure
# black-box recording and the background-error router ARE the typed
# route for paths with no caller to re-raise into
_SANCTIONED_SINKS = {
    "dump_postmortem", "_maybe_postmortem", "build_bundle",
    "note_background_error", "record_failure",
    # plan tagging: a caught typed error becomes a recorded
    # cannot-place-on-TPU reason the plan report surfaces
    "will_not_work",
    # flight-recorder breadcrumb: deliberate degradation that records
    # itself to the black box is routed, not swallowed
    "trace_event",
}

# handler callees that never count as relaying the caught exception
# (formatting/logging keeps the swallow a swallow)
_LOGGING_CALLEES = {
    "debug", "info", "warning", "warn", "error", "exception", "log",
    "print", "repr", "str", "format",
}


# ---------------------------------------------------------------------------
# per-function exception-flow scan
# ---------------------------------------------------------------------------

class _Handler:
    __slots__ = ("types", "lineno", "name", "has_raise", "relays",
                 "routes_sink", "deferred_names", "reraises_bare")

    def __init__(self, types: Tuple[str, ...], lineno: int,
                 name: Optional[str]):
        self.types = types
        self.lineno = lineno
        self.name = name          # `except X as name`
        self.has_raise = False    # any raise statement in the body
        self.reraises_bare = False
        self.relays = False       # caught var passed onward as an arg
        self.routes_sink = False  # calls a sanctioned sink
        self.deferred_names: Set[str] = set()  # v = ex; ... raise v


class _TryCtx:
    __slots__ = ("handlers", "lineno", "body_elems")

    def __init__(self, handlers: List[_Handler], lineno: int):
        self.handlers = handlers
        self.lineno = lineno
        # elements lexically inside the guarded body (indices)
        self.body_elems: List[int] = []

    def first_match(self, tax: Taxonomy, exc: str) -> Optional[_Handler]:
        for h in self.handlers:
            if any(tax.catches(t, exc) for t in h.types):
                return h
        return None


class _Elem:
    """One raising element: an explicit raise or a resolved callsite."""
    __slots__ = ("kind", "data", "lineno", "guards", "handler")

    def __init__(self, kind: str, data, lineno: int,
                 guards: Tuple[_TryCtx, ...],
                 handler: Optional[Tuple[_TryCtx, _Handler]] = None):
        self.kind = kind      # "raise" | "call" | "reraise"
        self.data = data      # exc name | tuple of callee fids | None
        self.lineno = lineno
        self.guards = guards  # innermost last
        self.handler = handler  # set for elements inside an except body


class _Acquire:
    __slots__ = ("label", "release_names", "lineno", "var",
                 "protected", "in_with")

    def __init__(self, label: str, release_names: Tuple[str, ...],
                 lineno: int, var: Optional[str]):
        self.label = label
        self.release_names = release_names
        self.lineno = lineno
        self.var = var
        self.protected = False
        self.in_with = False


class _FuncFlow(ast.NodeVisitor):
    """Single-function walk: raising elements with their lexical
    handler guards, release-obligation acquires, socket-deadline
    evidence."""

    def __init__(self, fi, call_targets: Dict[int, FrozenSet[str]],
                 obligations):
        self.fi = fi
        self.call_targets = call_targets
        self.obligations = obligations
        self.elems: List[_Elem] = []
        self.tries: List[_TryCtx] = []      # all Try nodes seen
        self.guard_stack: List[_TryCtx] = []
        self.handler_stack: List[Tuple[_TryCtx, _Handler]] = []
        self.acquires: List[_Acquire] = []
        self.release_lines: Dict[str, List[int]] = {}  # name -> linenos
        self.finally_release_names: Set[str] = set()
        # releases performed inside an except handler (cleanup-and-
        # reraise protects an obligation just like a finally does)
        self.handler_release_names: Set[str] = set()
        self.transfer_names: Set[str] = set()   # returned/stored/passed
        self.with_call_lines: Set[int] = set()
        self.settimeout_targets: Set[str] = set()
        # (kind, lineno, bound var, created-with-deadline)
        self.socket_calls: List[Tuple[str, int, str, bool]] = []
        self.self_socket_passed: List[int] = []  # self.request handed on
        self.self_socket_timeout = False
        self.in_finally = 0
        self.is_contextmanager = any(
            (isinstance(d, ast.Name) and d.id == "contextmanager") or
            (isinstance(d, ast.Attribute) and d.attr == "contextmanager")
            for d in getattr(fi.node, "decorator_list", ()))
        # local socket variables: var -> created-with-deadline?
        self.local_sockets: Dict[str, bool] = {}

    # -- helpers -------------------------------------------------------------
    def _guards(self) -> Tuple[_TryCtx, ...]:
        return tuple(self.guard_stack)

    def _add_elem(self, kind, data, lineno) -> None:
        e = _Elem(kind, data, lineno, self._guards(),
                  self.handler_stack[-1] if self.handler_stack else None)
        idx = len(self.elems)
        self.elems.append(e)
        for t in self.guard_stack:
            t.body_elems.append(idx)

    @staticmethod
    def _exc_name(node) -> Optional[str]:
        """Resolve a raise/except expression to a taxonomy name."""
        if isinstance(node, ast.Call):
            node = node.func
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            # socket.timeout / struct.error keep their dotted spelling
            if isinstance(node.value, ast.Name) and \
                    f"{node.value.id}.{node.attr}" in _BUILTIN_EXC_PARENTS:
                return f"{node.value.id}.{node.attr}"
            return node.attr
        return None

    # -- structure -----------------------------------------------------------
    def visit_Try(self, node: ast.Try) -> None:
        handlers: List[_Handler] = []
        for h in node.handlers:
            if h.type is None:
                types: Tuple[str, ...] = ("*",)
            elif isinstance(h.type, ast.Tuple):
                types = tuple(self._exc_name(e) or "*"
                              for e in h.type.elts)
            else:
                types = (self._exc_name(h.type) or "*",)
            handlers.append(_Handler(types, h.lineno, h.name))
        ctx = _TryCtx(handlers, node.lineno)
        self.tries.append(ctx)
        self.guard_stack.append(ctx)
        for st in node.body:
            self.visit(st)
        self.guard_stack.pop()
        # handler bodies run under the OUTER guards only
        for h, hrec in zip(node.handlers, handlers):
            self.handler_stack.append((ctx, hrec))
            for st in h.body:
                self.visit(st)
            self.handler_stack.pop()
            self._digest_handler(h, hrec)
        for st in node.orelse:
            self.visit(st)
        self.in_finally += 1
        for st in node.finalbody:
            self.visit(st)
        self.in_finally -= 1

    def _digest_handler(self, h: ast.ExceptHandler,
                        hrec: _Handler) -> None:
        """Classify what the handler does with what it caught."""
        for sub in ast.walk(h):
            if isinstance(sub, ast.Raise):
                hrec.has_raise = True
                if sub.exc is None:
                    hrec.reraises_bare = True
            elif isinstance(sub, ast.Assign) and hrec.name:
                if isinstance(sub.value, ast.Name) and \
                        sub.value.id == hrec.name:
                    for t in sub.targets:
                        if isinstance(t, ast.Name):
                            hrec.deferred_names.add(t.id)
            elif isinstance(sub, ast.Call):
                callee = None
                if isinstance(sub.func, ast.Attribute):
                    callee = sub.func.attr
                elif isinstance(sub.func, ast.Name):
                    callee = sub.func.id
                if callee in _SANCTIONED_SINKS:
                    hrec.routes_sink = True
                if hrec.name and callee not in _LOGGING_CALLEES:
                    for a in list(sub.args) + \
                            [k.value for k in sub.keywords]:
                        if isinstance(a, ast.Name) and \
                                a.id == hrec.name:
                            hrec.relays = True

    def visit_Raise(self, node: ast.Raise) -> None:
        if node.exc is None:
            if self.handler_stack:
                self._add_elem("reraise", None, node.lineno)
            return
        name = self._exc_name(node.exc)
        known = name is not None and (
            name in _BUILTIN_EXC_PARENTS or name[0:1].isupper())
        self._add_elem("raise", name if known else _DYNAMIC,
                       node.lineno)
        # a `raise v` where v was a deferred handler assignment keeps
        # the deferred types alive — record the raised name
        if isinstance(node.exc, ast.Name):
            self.transfer_names.add(node.exc.id)
        self.generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        for item in node.items:
            if isinstance(item.context_expr, ast.Call):
                self.with_call_lines.add(item.context_expr.lineno)
        self.generic_visit(node)

    def visit_Return(self, node: ast.Return) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                self.transfer_names.add(sub.id)
        self.generic_visit(node)

    def visit_Yield(self, node: ast.Yield) -> None:
        if node.value is not None:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name):
                    self.transfer_names.add(sub.id)
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        # self.x = v / container[k] = v: ownership leaves the frame
        stored_names = set()
        for sub in ast.walk(node.value):
            if isinstance(sub, ast.Name):
                stored_names.add(sub.id)
        for t in node.targets:
            if isinstance(t, (ast.Attribute, ast.Subscript)):
                self.transfer_names |= stored_names
        # acquire bound to a local: v = controller.admit(...)
        if isinstance(node.value, ast.Call):
            self._note_acquire(node.value, node.targets)
            self._note_socket_create(node.value, node.targets)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        callee_attr = None
        if isinstance(node.func, ast.Attribute):
            callee_attr = node.func.attr
        elif isinstance(node.func, ast.Name):
            callee_attr = node.func.id
        # raise-contribution element for resolved targets
        tgts = self.call_targets.get(node.lineno)
        if tgts:
            self._add_elem("call", tgts, node.lineno)
        # releases + transfers
        if callee_attr:
            self.release_lines.setdefault(callee_attr, []).append(
                node.lineno)
            if self.in_finally:
                self.finally_release_names.add(callee_attr)
            if self.handler_stack:
                self.handler_release_names.add(callee_attr)
            if callee_attr == "settimeout":
                if isinstance(node.func.value, ast.Name):
                    self.settimeout_targets.add(node.func.value.id)
                elif isinstance(node.func.value, ast.Attribute) and \
                        node.func.value.attr in ("request",
                                                 "connection"):
                    self.self_socket_timeout = True
        for a in list(node.args) + [k.value for k in node.keywords]:
            if isinstance(a, ast.Name):
                self.transfer_names.add(a.id)
            elif isinstance(a, ast.Attribute) and \
                    isinstance(a.value, ast.Name) and \
                    a.value.id == "self" and \
                    a.attr in ("request", "connection"):
                self.self_socket_passed.append(node.lineno)
        # bare-expression acquire (result dropped) still obliges
        self._note_acquire(node, ())
        self._note_socket_create(node, ())
        self.generic_visit(node)

    # -- obligations ---------------------------------------------------------
    def _note_acquire(self, call: ast.Call, targets) -> None:
        tgts = self.call_targets.get(call.lineno) or frozenset()
        if any(a.lineno == call.lineno for a in self.acquires):
            return  # visit_Assign already noted this call
        for label, suffix, releases in self.obligations:
            if not any(t.endswith(suffix) for t in tgts):
                continue
            var = None
            for t in targets:
                if isinstance(t, ast.Name):
                    var = t.id
                elif isinstance(t, ast.Tuple):
                    for e in t.elts:
                        if isinstance(e, ast.Name):
                            var = e.id
                            break
            acq = _Acquire(label, releases, call.lineno, var)
            acq.in_with = call.lineno in self.with_call_lines
            self.acquires.append(acq)
            return

    def _note_socket_create(self, call: ast.Call, targets) -> None:
        """socket.create_connection()/socket.socket() sites for R014."""
        f = call.func
        name = None
        if isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and f.value.id == "socket":
            name = f.attr
        elif isinstance(f, ast.Name) and \
                f.id in ("create_connection",):
            name = f.id
        if name not in ("create_connection", "socket"):
            return
        if any(c[1] == call.lineno for c in self.socket_calls):
            return  # visit_Assign already noted this call
        has_deadline = False
        if name == "create_connection":
            for k in call.keywords:
                if k.arg == "timeout" and not (
                        isinstance(k.value, ast.Constant) and
                        k.value.value is None):
                    has_deadline = True
            if len(call.args) >= 2:
                has_deadline = True
        var = None
        for t in targets:
            if isinstance(t, ast.Name):
                var = t.id
        if var:
            self.local_sockets[var] = has_deadline
        self.socket_calls.append((name, call.lineno, var or "",
                                  has_deadline))


# ---------------------------------------------------------------------------
# the analysis driver
# ---------------------------------------------------------------------------

class FlowAnalysis:
    """Raise sets, seams, diagnostics — plus the JSON-able artifact the
    fault-injection gate consumes."""

    def __init__(self):
        self.taxonomy: Optional[Taxonomy] = None
        self.raises: Dict[str, Set[str]] = {}       # fid -> escape set
        self.potential: Dict[str, Set[str]] = {}    # fid -> pre-narrow
        self.seams: Dict[str, str] = {}             # label -> fid
        self.seam_surfaces: Dict[str, Tuple[str, ...]] = {}
        self.origin: Dict[str, Set[str]] = {}       # exc -> relpaths
        # typed errors raisable anywhere REACHABLE from each seam over
        # the full (typed + CHA) call graph — the injection plan: the
        # gate must prove the seam propagates each one when it arises
        self.reach_typed: Dict[str, List[str]] = {}
        # exc name -> {(relpath, lineno)} explicit raise sites — the
        # monkeypatch points the fault gate derives injections from
        self.raise_sites: Dict[str, Set[Tuple[str, int]]] = {}
        self.diagnostics: List[Diagnostic] = []
        self.allow_sites: Dict[int, List[Tuple[str, int]]] = {}

    def seam_raises(self, label: str,
                    typed_only: bool = True) -> List[str]:
        fid = self.seams.get(label)
        if fid is None:
            return []
        out = self.raises.get(fid, set()) | \
            self.potential.get(fid, set())
        tax = self.taxonomy
        if typed_only:
            out = {e for e in out if tax is not None and
                   tax.is_typed(e)}
        return sorted(out - {_DYNAMIC})

    def artifact(self) -> Dict:
        """{'seams': {...}, 'taxonomy': {...}, 'injections': [...]}."""
        tax = self.taxonomy
        seams = {}
        injections = []
        for label in sorted(self.seams):
            fid = self.seams[label]
            escaped = sorted(self.raises.get(fid, set()) - {_DYNAMIC})
            typed = sorted(set(self.seam_raises(label)) |
                           set(self.reach_typed.get(label, [])))
            surface = self.seam_surfaces.get(label, ())
            leaks = []
            for e in escaped:
                # the R013 contract exactly: an *operational* untyped
                # exception whose origin is under the seam's own
                # surface (programming errors like ValueError /
                # TypeError and deep third-layer escapes stay in
                # "escapes" — visible, but not a leak verdict)
                if tax is None or tax.is_typed(e):
                    continue
                if e not in _UNTYPED_OPERATIONAL:
                    continue
                if any(_path_under(o, p)
                       for o in self.origin.get(e, set())
                       for p in surface):
                    leaks.append(e)
            seams[label] = {
                "fid": fid,
                "typed": typed,
                "untyped": leaks,
                "escapes": [e for e in escaped
                            if tax is not None and not tax.is_typed(e)],
            }
            for e in typed:
                injections.append({"seam": label, "error": e})
        taxonomy = {}
        if tax is not None:
            for name, info in sorted(tax.package_exc.items()):
                taxonomy[name] = {
                    "bases": list(info["bases"]),
                    "module": info["relpath"],
                    "raise_sites": sorted(
                        f"{p}:{ln}"
                        for p, ln in self.raise_sites.get(name, ())),
                }
        return {"seams": seams, "taxonomy": taxonomy,
                "injections": injections}


class _FlowAnalyzer:
    def __init__(self, sources: Dict[str, str], csan_analysis,
                 seams=SEAMS, obligations=None):
        self.sources = sources
        self.csan = csan_analysis
        self.seam_table = seams
        self.obligations = []
        for label, suffix, releases in (obligations or _OBLIGATIONS):
            self.obligations.append((label, suffix, releases))
        self.res = FlowAnalysis()

    def run(self) -> FlowAnalysis:
        res = self.res
        tax = Taxonomy(_package_exceptions(self.sources))
        res.taxonomy = tax
        funcs = self.csan.funcs

        # per-function scans
        flows: Dict[str, _FuncFlow] = {}
        for fid, fi in funcs.items():
            call_targets: Dict[int, FrozenSet[str]] = {}
            for tgts, via_cha, _held, ln in fi.callsites:
                if via_cha:
                    continue  # CHA edges are reachability-grade only
                real = frozenset(t for t in tgts
                                 if not t.startswith("ctor:"))
                if real:
                    call_targets[ln] = call_targets.get(
                        ln, frozenset()) | real
            fl = _FuncFlow(fi, call_targets, self.obligations)
            try:
                fl.visit(fi.node)
            except RecursionError:
                pass
            flows[fid] = fl

        # seam resolution
        for label, path_sfx, scope_sfx, surface in self.seam_table:
            for fid, fi in funcs.items():
                if fi.relpath.endswith(path_sfx) and (
                        fi.scope == scope_sfx or
                        fi.scope.endswith("." + scope_sfx)):
                    res.seams[label] = fid
                    res.seam_surfaces[label] = surface

        # raise-set fixpoint over the typed call graph
        raises: Dict[str, Set[str]] = {fid: set() for fid in funcs}
        origin: Dict[str, Set[str]] = {}

        def elem_contrib(fid: str, e: _Elem) -> Set[str]:
            if e.kind == "raise":
                if e.data != _DYNAMIC:
                    origin.setdefault(e.data, set()).add(
                        funcs[fid].relpath)
                return {e.data}
            if e.kind == "call":
                out: Set[str] = set()
                for t in e.data:
                    out |= raises.get(t, set())
                return out
            if e.kind == "reraise" and e.handler is not None:
                ctx, h = e.handler
                body_pot = set()
                fl = flows[fid]
                for idx in ctx.body_elems:
                    body_pot |= elem_contrib(fid, fl.elems[idx])
                return {exc for exc in body_pot
                        if ctx.first_match(tax, exc) is h}
            return set()

        def escape_set(fid: str) -> Set[str]:
            fl = flows[fid]
            out: Set[str] = set()
            for e in fl.elems:
                contrib = elem_contrib(fid, e)
                for ctx in reversed(e.guards):
                    if not contrib:
                        break
                    survived = set()
                    for exc in contrib:
                        h = ctx.first_match(tax, exc)
                        if h is None or h.reraises_bare or \
                                (h.deferred_names and
                                 h.deferred_names & fl.transfer_names):
                            survived.add(exc)
                    contrib = survived
                out |= contrib
            return out

        for _round in range(24):
            changed = False
            for fid in funcs:
                new = escape_set(fid)
                if new != raises[fid]:
                    raises[fid] = new
                    changed = True
            if not changed:
                break
        res.raises = raises
        res.origin = origin

        # pre-narrowing potential sets (what a seam's body can see
        # before its own handlers narrow it) — drives the injection
        # plan for seams that sanction everything (background loops)
        for fid in funcs:
            fl = flows[fid]
            pot: Set[str] = set()
            for e in fl.elems:
                pot |= elem_contrib(fid, e)
            res.potential[fid] = pot - {_DYNAMIC}

        # explicit raise sites (the monkeypatch points)
        for fid, fl in flows.items():
            for e in fl.elems:
                if e.kind == "raise" and e.data != _DYNAMIC:
                    res.raise_sites.setdefault(e.data, set()).add(
                        (funcs[fid].relpath, e.lineno))

        # injection plan: typed errors raisable anywhere reachable
        # from each seam over the FULL call graph (typed + CHA edges —
        # reachability-grade is exactly right here: the plan asks
        # "can this error arise under this seam at runtime?")
        # subclass-override closure: a resolved call to C.m can land in
        # any override D.m at runtime (the tpucsan typed edge stops at
        # the declared class — fine for lock order, too narrow for
        # "which errors can arise under this seam")
        children: Dict[str, Set[str]] = {}
        for relpath, src in self.sources.items():
            try:
                tree = ast.parse(src)
            except SyntaxError:
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.ClassDef):
                    for b in node.bases:
                        bn = b.id if isinstance(b, ast.Name) else (
                            b.attr if isinstance(b, ast.Attribute)
                            else None)
                        if bn:
                            children.setdefault(bn, set()).add(
                                node.name)
        subs: Dict[str, Set[str]] = {}

        def _descendants(cls: str) -> Set[str]:
            if cls not in subs:
                subs[cls] = set()
                for c in children.get(cls, ()):
                    subs[cls].add(c)
                    subs[cls] |= _descendants(c)
            return subs[cls]

        method_index: Dict[Tuple[str, str], Set[str]] = {}
        for fid, fi in funcs.items():
            parts = fi.scope.split(".")
            if len(parts) >= 2:
                method_index.setdefault(
                    (parts[-2], parts[-1]), set()).add(fid)

        def _overrides(tgt: str) -> Set[str]:
            scope = tgt.split("::", 1)[-1].split(".")
            if len(scope) < 2:
                return set()
            cls, m = scope[-2], scope[-1]
            out: Set[str] = set()
            for d in _descendants(cls):
                out |= method_index.get((d, m), set())
            return out

        full_edges: Dict[str, Set[str]] = {}
        for fid, fi in funcs.items():
            out = full_edges.setdefault(fid, set())
            for tgts, _via_cha, _held, _ln in fi.callsites:
                for t in tgts:
                    if t in funcs:
                        out.add(t)
                        out |= _overrides(t)
        raw_typed: Dict[str, Set[str]] = {}
        for fid, fl in flows.items():
            raw_typed[fid] = {
                e.data for e in fl.elems
                if e.kind == "raise" and e.data != _DYNAMIC and
                tax.is_typed(e.data)}
        for label, seam_fid in res.seams.items():
            seen = {seam_fid}
            work = [seam_fid]
            while work:
                cur = work.pop()
                for nxt in full_edges.get(cur, ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        work.append(nxt)
            reach: Set[str] = set()
            for fid in seen:
                reach |= raw_typed.get(fid, set())
            res.reach_typed[label] = sorted(reach)
        for label, delegates in _SEAM_DELEGATES.items():
            if label not in res.seams:
                continue
            merged = set(res.reach_typed.get(label, []))
            for dl in delegates:
                merged |= set(res.reach_typed.get(dl, []))
            res.reach_typed[label] = sorted(merged)

        self._rule_r011(flows, tax, elem_contrib)
        self._rule_r012(flows, raises)
        self._rule_r013(tax)
        self._rule_r014(flows)
        return res

    # -- rules ---------------------------------------------------------------
    def _rule_r011(self, flows, tax, elem_contrib) -> None:
        res = self.res
        for fid, fl in flows.items():
            fi = self.csan.funcs[fid]
            for ctx in fl.tries:
                body_pot: Set[str] = set()
                for idx in ctx.body_elems:
                    body_pot |= elem_contrib(fid, fl.elems[idx])
                for h in ctx.handlers:
                    # only OVERBROAD catches: `except TpuShuffleX`
                    # is the taxonomy being dispatched on — the whole
                    # point of typing the errors
                    if not tax.is_broad(h.types):
                        continue
                    matched = {exc for exc in body_pot
                               if ctx.first_match(tax, exc) is h}
                    typed_matched = sorted(
                        e for e in matched if tax.is_typed(e))
                    if not typed_matched:
                        continue
                    if h.has_raise or h.relays or h.routes_sink or \
                            (h.deferred_names and
                             h.deferred_names & fl.transfer_names):
                        continue
                    shown = ", ".join(typed_matched[:4])
                    if len(typed_matched) > 4:
                        shown += ", ..."
                    d = R011.diag(
                        f"{fi.scope}: bare/broad except swallows "
                        f"typed engine error(s) {shown} without "
                        f"re-raise, relay or a sanctioned sink",
                        loc=f"{fi.relpath}:{h.lineno}")
                    res.diagnostics.append(d)
                    res.allow_sites[id(d)] = [(fi.relpath, h.lineno)]

    def _rule_r012(self, flows, raises) -> None:
        res = self.res
        for fid, fl in flows.items():
            fi = self.csan.funcs[fid]
            if fl.is_contextmanager or fi.is_init:
                continue
            for acq in fl.acquires:
                if acq.in_with:
                    continue
                if any(r in fl.finally_release_names or
                       r in fl.handler_release_names
                       for r in acq.release_names):
                    continue
                if acq.var and acq.var in fl.transfer_names:
                    continue  # ownership left this frame
                # a raising successor between acquire and the release
                release_after = [
                    ln for r in acq.release_names
                    for ln in fl.release_lines.get(r, [])
                    if ln > acq.lineno]
                horizon = min(release_after) if release_after \
                    else float("inf")
                risky = False
                for e in fl.elems:
                    if not (acq.lineno < e.lineno <= horizon):
                        continue
                    if e.kind == "raise":
                        risky = True
                        break
                    if e.kind == "call" and any(
                            raises.get(t) for t in e.data):
                        risky = True
                        break
                if not risky:
                    continue
                d = R012.diag(
                    f"{fi.scope}: {acq.label} acquired here can leak "
                    f"— a raising successor unwinds before "
                    f"{'/'.join(acq.release_names)}() and no finally/"
                    f"with/ownership-transfer protects it",
                    loc=f"{fi.relpath}:{acq.lineno}")
                res.diagnostics.append(d)
                res.allow_sites[id(d)] = [(fi.relpath, acq.lineno)]

    def _rule_r013(self, tax) -> None:
        res = self.res
        for label, fid in res.seams.items():
            fi = self.csan.funcs[fid]
            surface = res.seam_surfaces.get(label, ())
            for exc in sorted(res.raises.get(fid, set())):
                if exc == _DYNAMIC or tax.is_typed(exc):
                    continue
                if exc not in _UNTYPED_OPERATIONAL:
                    continue
                origins = res.origin.get(exc, set())
                in_scope = [o for o in origins
                            if any(_path_under(o, p)
                                   for p in surface)]
                if not in_scope:
                    continue
                d = R013.diag(
                    f"seam {label} ({fi.scope}) leaks untyped {exc} "
                    f"raised in {sorted(in_scope)[0]} — callers "
                    f"dispatch on the typed taxonomy",
                    loc=f"{fi.relpath}:{fi.node.lineno}")
                res.diagnostics.append(d)
                res.allow_sites[id(d)] = [
                    (fi.relpath, fi.node.lineno)]

    def _rule_r014(self, flows) -> None:
        res = self.res
        reachable: Set[str] = set()
        for root, seen in self.csan.reachable.items():
            reachable |= seen
        reachable |= set(self.csan.roots)
        for fid, fl in flows.items():
            if fid not in reachable:
                continue
            fi = self.csan.funcs[fid]
            for name, lineno, var, created_deadline in fl.socket_calls:
                deadline = created_deadline or (
                    fl.local_sockets.get(var, False) if var else False)
                if name == "create_connection" and not deadline and \
                        var not in fl.settimeout_targets:
                    d = R014.diag(
                        f"{fi.scope}: socket.create_connection on a "
                        f"thread-root path without an explicit "
                        f"timeout", loc=f"{fi.relpath}:{lineno}")
                    res.diagnostics.append(d)
                    res.allow_sites[id(d)] = [(fi.relpath, lineno)]
                elif name == "socket" and var and \
                        var not in fl.settimeout_targets and \
                        not deadline:
                    d = R014.diag(
                        f"{fi.scope}: socket() created on a thread-"
                        f"root path never gets settimeout()",
                        loc=f"{fi.relpath}:{lineno}")
                    res.diagnostics.append(d)
                    res.allow_sites[id(d)] = [(fi.relpath, lineno)]
            if fl.self_socket_passed and not fl.self_socket_timeout:
                lineno = min(fl.self_socket_passed)
                d = R014.diag(
                    f"{fi.scope}: accepted connection "
                    f"(self.request/self.connection) used without "
                    f"settimeout() — a hung peer pins this handler "
                    f"thread forever", loc=f"{fi.relpath}:{lineno}")
                res.diagnostics.append(d)
                res.allow_sites[id(d)] = [(fi.relpath, lineno)]


# ---------------------------------------------------------------------------
# public API (mirrors concurrency.py)
# ---------------------------------------------------------------------------

def analyze_sources(sources: Dict[str, str],
                    roots: Optional[Iterable[str]] = None,
                    seams=SEAMS) -> FlowAnalysis:
    """Full pass over in-memory sources (fixtures, tests)."""
    from . import concurrency
    csan = concurrency.analyze_sources(sources, roots=roots)
    return _FlowAnalyzer(sources, csan, seams=seams).run()


_REPO_CACHE: Dict[str, FlowAnalysis] = {}


def analyze_repo(root: Optional[str] = None,
                 refresh: bool = False) -> FlowAnalysis:
    from . import concurrency
    from .repo_lint import _package_root
    key = os.path.abspath(root or _package_root())
    if refresh or key not in _REPO_CACHE:
        sources = concurrency._package_sources(root)
        csan = concurrency.analyze_repo(root, refresh=refresh)
        _REPO_CACHE[key] = _FlowAnalyzer(sources, csan).run()
    return _REPO_CACHE[key]


def repo_diagnostics(root: Optional[str] = None) -> List[Diagnostic]:
    """TPU-R011..R014 over the package, allow-annotations honored."""
    from . import concurrency
    res = analyze_repo(root)
    return concurrency.filter_allowed(res, concurrency._package_sources(root))


def raise_graph_artifact(root: Optional[str] = None) -> Dict:
    """The JSON artifact `tools lint --raise-graph` dumps and the
    --faults gate consumes: per-seam raise sets + the injection plan."""
    return analyze_repo(root).artifact()


# sample constructors for typed errors with non-trivial signatures —
# the fault-injection gate instantiates every typed error in the plan
_SAMPLE_ARGS: Dict[str, tuple] = {
    "TpuShufflePeerDeadError": ("peer-1", "tpufsan injected"),
    "TpuShuffleTruncatedFrameError": (128, 7),
    "TpuShuffleStaleFrameError": (1, 2),
    "TpuShuffleVersionError": (9,),
}


def construct_error(name: str,
                    root: Optional[str] = None) -> BaseException:
    """Instantiate the typed error ``name`` for fault injection."""
    import importlib
    res = analyze_repo(root)
    info = res.taxonomy.package_exc.get(name) if res.taxonomy else None
    if info is None:
        raise KeyError(f"unknown typed error {name!r}")
    relpath = info["relpath"]
    if relpath.startswith(_PKG_PREFIX):
        relpath = relpath[len(_PKG_PREFIX):]
    relmod = relpath[:-3].replace("/", ".")
    mod = importlib.import_module(f"spark_rapids_tpu.{relmod}")
    cls = getattr(mod, name)
    args = _SAMPLE_ARGS.get(name, (f"tpufsan injected {name}",))
    try:
        return cls(*args)
    except TypeError:
        return cls()
