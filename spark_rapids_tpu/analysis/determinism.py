"""tpudsan: determinism & replay-safety pass.

ROADMAP's lineage-based recovery ("recompute lost map partitions") and
fingerprint-keyed fragment caching both assume a recomputed plan
fragment reproduces its output bit-for-bit.  The reference gets that by
convention; this pass gets it by proof, the same way tmsan proved memory
bounds and tpufsan proved typed exception flow: operators DECLARE a
replay class via ``Exec.determinism()``, the pass composes the
declarations bottom-up alongside the interp's schema/residency states,
and the permuted-replay oracle (``devtools/run_lint.py --dsan``) keeps
the declarations honest by replaying golden map stages under permuted
batch arrival order and a changed input split, asserting
content-digest-identical shuffle blocks wherever ``order_stable`` or
better is claimed.

The replay-class lattice (strongest first):

  bit_exact        recompute reproduces the output bytes exactly,
                   whatever the batch arrival order or input split
  order_stable     the output MULTISET per partition is invariant under
                   batch arrival order and input-split changes; row
                   order within a partition may differ (hash-table
                   emission order, probe order)
  order_dependent  output VALUES depend on arrival order — e.g. a float
                   accumulation whose grouping follows batch arrival
  nondeterministic RNG, wall clock, or iteration-order effects: two
                   runs may disagree on content

Rules:

  TPU-L016  a subtree feeding an exchange or cacheable fragment is
            weaker than order_stable without a stabilizing barrier;
            repairable when the weakness is a canonicalizable merge
            (``try_stabilize_repair`` forces the aggregate's keyed
            canonical merge, the same pre-flight shape as the L014
            out-of-core repair)
  TPU-L017  a plan-fragment fingerprint field in obs/history.py
            incorporates a volatile input (wall-clock, session-local
            state), so a fingerprint-keyed cache hit could serve stale
            or unreproducible data
  TPU-R015  wall-clock / unseeded RNG / set-iteration order / id()-keyed
            ordering on a result-affecting path in exec/, ops/, expr/
            or shuffle/ without a sanctioned helper
  TPU-R016  a float reduction folded in batch-arrival order (no declared
            tolerance, no canonical keyed merge): partials regrouped by
            a different split or arrival order change the result
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

from .diagnostics import Diagnostic, ERROR, register_rule

# ---------------------------------------------------------------------------
# rule registrations
# ---------------------------------------------------------------------------

L016 = register_rule(
    "TPU-L016", ERROR, "replay-unstable subtree feeds an exchange",
    "The subtree below a shuffle/ICI exchange or cache write composes to "
    "a replay class weaker than order_stable: a recomputed map task "
    "(lineage recovery) or a fingerprint-keyed cache hit would not "
    "reproduce the blocks it replaces.  Repairable when the weakness is "
    "a canonicalizable merge — the pre-flight forces the aggregate's "
    "keyed canonical merge (stable_merge), the same downgrade machinery "
    "as TPU-L011/L014.")

L017 = register_rule(
    "TPU-L017", ERROR, "volatile input in a plan-fragment fingerprint",
    "A field of the query/fragment fingerprint (obs/history.py "
    "DETERMINISTIC_FIELDS) incorporates a volatile input — wall-clock, "
    "timing, session-local state — so two identical plans fingerprint "
    "differently (cache misses) or two different executions collide "
    "(stale cache hits).  Deterministic and timing field sets must be "
    "disjoint and deterministic field names must not be time-derived.")

R015 = register_rule(
    "TPU-R015", ERROR, "volatile source on a result-affecting path",
    "Wall-clock reads (time.time/time_ns, datetime.now/utcnow), "
    "unseeded RNG (random.*, np.random.* without an explicit seed), "
    "iteration over a set (PYTHONHASHSEED-dependent order across "
    "processes), or id()-keyed sorting inside exec/, ops/, expr/ or "
    "shuffle/: any of these on a result path makes a recomputed "
    "partition differ from the lost one.  Seeded generators "
    "(np.random.RandomState(seed), random.Random(seed)) and "
    "sorted(set(...)) are sanctioned; deliberate sites are annotated "
    "`# tpulint: allow[TPU-R015]` in place.")

R016 = register_rule(
    "TPU-R016", ERROR, "arrival-order float accumulation",
    "A float value is folded (`+=`) across batches in arrival order "
    "inside exec/: float addition is not associative, so a different "
    "batch arrival order or input split changes the result.  Declare a "
    "tolerance, canonicalize with a keyed merge "
    "(TpuHashAggregateExec.stable_merge), or tree-reduce in a "
    "content-determined order.  Deliberate sites are annotated "
    "`# tpulint: allow[TPU-R016]` in place.")

# ---------------------------------------------------------------------------
# the replay-class lattice
# ---------------------------------------------------------------------------

BIT_EXACT = "bit_exact"
ORDER_STABLE = "order_stable"
ORDER_DEPENDENT = "order_dependent"
NONDETERMINISTIC = "nondeterministic"

RANK = {BIT_EXACT: 3, ORDER_STABLE: 2, ORDER_DEPENDENT: 1,
        NONDETERMINISTIC: 0}
CLASSES = (BIT_EXACT, ORDER_STABLE, ORDER_DEPENDENT, NONDETERMINISTIC)


def meet(a: str, b: str) -> str:
    """Weaker of two replay classes (lattice meet)."""
    return a if RANK[a] <= RANK[b] else b


class Determinism:
    """One operator's declared replay behavior.

    `cls` is the operator's own contribution assuming its inputs arrive
    bit-identically; composition with the children happens in
    ``classify_plan``.  `order_sensitive_selection` marks operators
    whose output CONTENT depends on input row order (limits, offset-
    keyed sampling) — sound only above an order-establishing sort, else
    the effective class degrades to order_dependent.
    `establishes_order` marks operators whose output row order is a
    function of content (sorts), which is what makes a selection above
    them stable and satisfies the TPU-L016 barrier requirement.
    `partition_scoped` marks operators whose output values depend on
    the partition grouping itself (PARTIAL-mode aggregates): the
    permuted-replay oracle skips the changed-split leg for such
    subtrees (arrival-permutation identity is still asserted).
    `canonicalizable` marks a weakness ``try_stabilize_repair`` can fix
    by forcing the operator's canonical keyed merge."""

    __slots__ = ("cls", "reason", "order_sensitive_selection",
                 "establishes_order", "partition_scoped",
                 "canonicalizable")

    def __init__(self, cls: str, reason: str = "",
                 order_sensitive_selection: bool = False,
                 establishes_order: bool = False,
                 partition_scoped: bool = False,
                 canonicalizable: bool = False):
        if cls not in RANK:
            raise ValueError(f"unknown replay class {cls!r}")
        self.cls = cls
        self.reason = reason
        self.order_sensitive_selection = order_sensitive_selection
        self.establishes_order = establishes_order
        self.partition_scoped = partition_scoped
        self.canonicalizable = canonicalizable

    def __repr__(self):
        return f"Determinism({self.cls!r}, {self.reason!r})"


_DEFAULT = Determinism(BIT_EXACT, "pure streaming operator (default)")


def node_determinism(node) -> Determinism:
    """An operator's declaration, defaulted: None means pure streaming
    (row-wise function of input, no order/time/RNG sensitivity)."""
    d = node.determinism()
    return d if d is not None else _DEFAULT


# ---------------------------------------------------------------------------
# bottom-up composition over a physical plan
# ---------------------------------------------------------------------------

class DeterminismResult:
    """Per-node effective replay classes for one plan, plus the TPU-L016
    diagnostics.  `classes[id(node)]` is the class of the SUBTREE rooted
    at node (own declaration met with every child's effective class)."""

    def __init__(self):
        self.classes: Dict[int, str] = {}
        self.reasons: Dict[int, str] = {}
        self.partition_scoped: Dict[int, bool] = {}
        self.repairs: List[str] = []
        self.diags: List[Diagnostic] = []

    def effective(self, node) -> str:
        return self.classes.get(id(node), NONDETERMINISTIC)

    def reason(self, node) -> str:
        return self.reasons.get(id(node), "")

    def is_partition_scoped(self, node) -> bool:
        return self.partition_scoped.get(id(node), False)


def _is_fragment_boundary(node) -> bool:
    """Nodes whose child subtree must replay order_stable or better:
    exchange writes (lineage recovery recomputes the map side) and
    cache writes (fingerprint-keyed reuse serves the stored blocks)."""
    from ..io.cached_batch import CacheWriteExec
    from ..parallel.ici_exec import IciExchangeExec
    from ..shuffle.exchange import ShuffleExchangeExec
    return isinstance(node, (ShuffleExchangeExec, IciExchangeExec,
                             CacheWriteExec))


def _classify(node, res: DeterminismResult) -> str:
    child_eff = [_classify(c, res) for c in node.children]
    d = node_determinism(node)
    own, reason = d.cls, d.reason
    if d.order_sensitive_selection and node.children and \
            not all(node_determinism(c).establishes_order
                    for c in node.children):
        if RANK[own] > RANK[ORDER_DEPENDENT]:
            own = ORDER_DEPENDENT
            reason = (f"{node.name}: order-sensitive selection with no "
                      f"order-establishing sort below — which rows are "
                      f"selected follows batch arrival")
    eff = own
    weakest = f"{node.name}: {reason}" if reason else node.name
    for c, ce in zip(node.children, child_eff):
        if RANK[ce] < RANK[eff]:
            eff, weakest = ce, res.reasons[id(c)]
    scoped = d.partition_scoped or \
        any(res.partition_scoped[id(c)] for c in node.children)
    res.classes[id(node)] = eff
    res.reasons[id(node)] = weakest if RANK[eff] < RANK[BIT_EXACT] \
        else f"{node.name}: {reason}" if reason else ""
    res.partition_scoped[id(node)] = scoped
    return eff


def classify_plan(root, conf=None) -> DeterminismResult:
    """Compose declared replay classes bottom-up and emit TPU-L016 for
    every fragment boundary whose input subtree is weaker than
    order_stable.  Pure — never mutates the plan (the repair lives in
    ``try_stabilize_repair``, applied by the pre-flight)."""
    res = DeterminismResult()
    _classify(root, res)
    _emit_l016(root, res, path="")
    return res


def _emit_l016(node, res: DeterminismResult, path: str) -> None:
    here = f"{path} > {node.name}" if path else node.name
    if _is_fragment_boundary(node) and node.children:
        child = node.children[0]
        eff = res.effective(child)
        if RANK[eff] < RANK[ORDER_STABLE]:
            fix = ", ".join(_canonical_sites(child))
            hint = (f" — repairable: force the canonical keyed merge on "
                    f"[{fix}]" if fix else
                    " — no stabilizing barrier available; recomputed "
                    "blocks may not match the lost ones")
            res.diags.append(L016.diag(
                f"subtree feeding {node.name} composes to {eff} "
                f"({res.reason(child)}); lineage recovery and "
                f"fingerprint-keyed caching need order_stable or "
                f"better{hint}", loc=here, node=node))
    for c in node.children:
        _emit_l016(c, res, here)


def _canonical_sites(node) -> List[str]:
    out = []
    if node_determinism(node).canonicalizable:
        out.append(node.name)
    for c in node.children:
        out.extend(_canonical_sites(c))
    return out


def try_stabilize_repair(root, node, conf) -> bool:
    """TPU-L016 repair: force the canonical keyed merge on every
    canonicalizable operator under the flagged boundary `node`
    (aggregate ``stable_merge`` — sorts partial buffers by group key +
    value words before folding, making the accumulation order a
    function of content, not arrival).  Returns True when the subtree
    re-classifies to order_stable or better; the caller treats that
    like the L014 out-of-core repair (no host flip needed)."""
    flipped = []

    def force(n):
        if node_determinism(n).canonicalizable and \
                getattr(n, "stable_merge", True) is False:
            n.stable_merge = True
            n.__dict__.pop("_jit_key", None)  # invalidate cached_property
            flipped.append(n)
        for c in n.children:
            force(c)

    force(node)
    if not flipped:
        return False
    res = DeterminismResult()
    child = node.children[0] if node.children else node
    eff = _classify(child, res)
    if RANK[eff] >= RANK[ORDER_STABLE]:
        return True
    for n in flipped:  # repair did not reach order_stable: undo
        n.stable_merge = False
        n.__dict__.pop("_jit_key", None)
    return False


def format_classes(root, conf=None) -> str:
    """Human-oriented per-subtree replay classes (the --determinism
    plan-mode printer, sibling of interp.format_states)."""
    res = classify_plan(root, conf)
    lines: List[str] = []

    def walk(node, depth):
        eff = res.effective(node)
        own = node_determinism(node)
        extra = ""
        if RANK[eff] < RANK[BIT_EXACT] and res.reason(node):
            extra = f"  <- {res.reason(node)}"
        if res.is_partition_scoped(node):
            extra += "  [partition-scoped]"
        lines.append(f"{'  ' * depth}{node.name}: {eff}"
                     f" (declares {own.cls}){extra}")
        for c in node.children:
            walk(c, depth + 1)

    walk(root, 0)
    for d in res.diags:
        lines.append(d.render())
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------------------
# TPU-L017: fingerprint hygiene (obs/history.py)
# ---------------------------------------------------------------------------

_VOLATILE_FIELD = re.compile(
    r"(wall|time|_ms($|_)|_ns($|_)|seconds|session|pid|stamp|random|"
    r"uptime)", re.I)


def fingerprint_hygiene_diagnostics(
        deterministic: Optional[Iterable[str]] = None,
        timing: Optional[Iterable[str]] = None) -> List[Diagnostic]:
    """TPU-L017 over the live fingerprint schema: the deterministic
    field set (what fragment caching keys on) must be disjoint from the
    timing set and free of volatile names.  Parameters are injectable
    so the gate can prove the check is not vacuous."""
    if deterministic is None or timing is None:
        from ..obs import history
        deterministic = history.DETERMINISTIC_FIELDS
        timing = history.TIMING_FIELDS
    loc = "spark_rapids_tpu/obs/history.py"
    diags: List[Diagnostic] = []
    overlap = sorted(set(deterministic) & set(timing))
    for f in overlap:
        diags.append(L017.diag(
            f"fingerprint field {f} is listed both deterministic and "
            f"timing: a cache keyed on it would miss on identical "
            f"plans and collide across executions", loc=loc))
    for f in deterministic:
        if f in overlap:
            continue
        if _VOLATILE_FIELD.search(f):
            diags.append(L017.diag(
                f"deterministic fingerprint field {f} looks "
                f"time-derived; a fingerprint-keyed cache hit could "
                f"serve stale data", loc=loc))
    return diags


# ---------------------------------------------------------------------------
# TPU-R015/R016: the repo AST pass
# ---------------------------------------------------------------------------

_R015_PATHS = ("spark_rapids_tpu/exec/", "spark_rapids_tpu/ops/",
               "spark_rapids_tpu/expr/", "spark_rapids_tpu/shuffle/")
_R016_PATHS = ("spark_rapids_tpu/exec/",)

# np.random constructors that take an explicit seed are the sanctioned
# route (serve_map's RandomState(seed) synthetic-data generator)
_SEEDED_NP_RANDOM = {"RandomState", "default_rng", "SeedSequence",
                     "Generator"}
_WALL_CLOCK = {"time", "time_ns"}


def _func_chain(f) -> List[str]:
    """Dotted name parts of a call target, outermost first."""
    parts: List[str] = []
    while isinstance(f, ast.Attribute):
        parts.append(f.attr)
        f = f.value
    if isinstance(f, ast.Name):
        parts.append(f.id)
    return list(reversed(parts))


class _VolatileSourceVisitor:
    """TPU-R015 over one module (scope tracking via repo_lint's
    _ScopedVisitor, shared with every other repo rule)."""

    def __init__(self, relpath: str):
        from .repo_lint import _ScopedVisitor
        outer = self

        class V(_ScopedVisitor):
            def visit_Call(self, node):
                outer._call(node, self.scope)
                self.generic_visit(node)

            def visit_For(self, node):
                outer._iter(node.iter, self.scope)
                self.generic_visit(node)

            def visit_comprehension(self, node):
                outer._iter(node.iter, self.scope)
                self.generic_visit(node)

        self.relpath = relpath
        self.diags: List[Diagnostic] = []
        self._v = V()

    def visit(self, tree):
        self._v.visit(tree)

    def _diag(self, msg: str, scope: str, lineno: int):
        self.diags.append(R015.diag(
            f"{msg} in {scope}", loc=f"{self.relpath}:{lineno}"))

    def _call(self, node, scope: str):
        chain = _func_chain(node.func)
        if not chain:
            return
        head, tail = chain[0].lstrip("_"), chain[-1]
        if head == "time" and len(chain) == 2 and tail in _WALL_CLOCK:
            self._diag(f"wall-clock read time.{tail}() on a result "
                       f"path", scope, node.lineno)
        elif tail in ("now", "utcnow") and "datetime" in chain:
            self._diag(f"wall-clock read {'.'.join(chain)}() on a "
                       f"result path", scope, node.lineno)
        elif head == "random" and len(chain) == 2 and \
                tail not in ("Random", "SystemRandom"):
            self._diag(f"unseeded RNG random.{tail}()", scope,
                       node.lineno)
        elif len(chain) >= 3 and chain[-2] == "random" and \
                chain[0].lstrip("_") in ("np", "numpy") and \
                tail not in _SEEDED_NP_RANDOM:
            self._diag(f"unseeded RNG {'.'.join(chain)}()", scope,
                       node.lineno)
        elif tail in ("sorted", "sort") and any(
                kw.arg == "key" and isinstance(kw.value, ast.Name) and
                kw.value.id == "id" for kw in node.keywords):
            self._diag("id()-keyed sort: addresses differ across "
                       "processes and replays", scope, node.lineno)

    def _iter(self, it, scope: str):
        if isinstance(it, ast.Set):
            self._diag("iteration over a set literal "
                       "(PYTHONHASHSEED-dependent order)", scope,
                       it.lineno)
        elif isinstance(it, ast.Call):
            chain = _func_chain(it.func)
            if chain and chain[-1] in ("set", "frozenset") and \
                    len(chain) == 1:
                self._diag(f"iteration over {chain[-1]}() "
                           f"(PYTHONHASHSEED-dependent order); wrap in "
                           f"sorted()", scope, it.lineno)


_ARRIVAL_NAME = re.compile(
    r"(^|_)(batch(es)?|block(s)?|partial(s)?|chunk(s)?|mats?|streams?)$",
    re.I)
_ARRIVAL_CALLS = {"execute_partition", "blocks", "read_reduce_blocks",
                  "blocks_for_reduce"}
# integer bookkeeping folded across batches is fine — only value-level
# float folds regroup under a different split
_BOOKKEEPING = re.compile(
    r"(rows|bytes|offset|idx|index|pos|base|seen|done|len)", re.I)


def _is_arrival_iter(it) -> Optional[str]:
    if isinstance(it, ast.Name) and _ARRIVAL_NAME.search(it.id):
        return it.id
    if isinstance(it, ast.Call):
        chain = _func_chain(it.func)
        if chain and chain[-1] in _ARRIVAL_CALLS:
            return f"{chain[-1]}()"
    return None


class _ArrivalFoldVisitor:
    """TPU-R016 over one module: `acc += f(batch)` inside a for-loop
    over an arrival-ordered source, where acc is not integer
    bookkeeping — the float-fold order then equals arrival order."""

    def __init__(self, relpath: str):
        from .repo_lint import _ScopedVisitor, _is_tally_name
        outer = self
        self._is_tally = _is_tally_name

        class V(_ScopedVisitor):
            def visit_For(self, node):
                outer._for(node, self.scope)
                self.generic_visit(node)

        self.relpath = relpath
        self.diags: List[Diagnostic] = []
        self._v = V()

    def visit(self, tree):
        self._v.visit(tree)

    def _for(self, node, scope: str):
        src = _is_arrival_iter(node.iter)
        if src is None:
            return
        loop_names = {n.id for n in ast.walk(node.target)
                      if isinstance(n, ast.Name)}
        for stmt in ast.walk(node):
            if not (isinstance(stmt, ast.AugAssign) and
                    isinstance(stmt.op, ast.Add)):
                continue
            tgt = stmt.target
            name = tgt.id if isinstance(tgt, ast.Name) else \
                tgt.attr if isinstance(tgt, ast.Attribute) else None
            if name is None or self._is_tally(name) or \
                    _BOOKKEEPING.search(name):
                continue
            refs = {n.id for n in ast.walk(stmt.value)
                    if isinstance(n, ast.Name)}
            if not (refs & loop_names):
                continue
            if isinstance(stmt.value, ast.Call):
                chain = _func_chain(stmt.value.func)
                if chain and chain[-1] in ("int", "len", "list",
                                           "tuple"):
                    continue
            self.diags.append(R016.diag(
                f"{name} += folded across {src} in arrival order in "
                f"{scope}: float accumulation order follows batch "
                f"arrival — canonicalize (keyed merge / tree reduce) "
                f"or declare a tolerance",
                loc=f"{self.relpath}:{stmt.lineno}"))


def repo_diagnostics(root: Optional[str] = None) -> List[Diagnostic]:
    """TPU-R015/R016 over the package source plus the TPU-L017
    fingerprint-hygiene registry check; appended to lint_repo like the
    tpucsan and tpufsan passes."""
    from .repo_lint import _allowed_lines, _package_root, _py_files
    root = root or _package_root()
    diags: List[Diagnostic] = []
    for path in _py_files(root):
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        r015 = any(relpath.startswith(p) for p in _R015_PATHS)
        r016 = any(relpath.startswith(p) for p in _R016_PATHS)
        if not (r015 or r016):
            continue
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=relpath)
        except SyntaxError:
            continue  # TPU-R000 already reported by the core pass
        file_diags: List[Diagnostic] = []
        if r015:
            v = _VolatileSourceVisitor(relpath)
            v.visit(tree)
            file_diags.extend(v.diags)
        if r016:
            fv = _ArrivalFoldVisitor(relpath)
            fv.visit(tree)
            file_diags.extend(fv.diags)
        allowed = _allowed_lines(source) if file_diags else {}
        for d in file_diags:
            lineno = int(d.loc.rsplit(":", 1)[-1]) if ":" in d.loc else -1
            if lineno in allowed.get(d.code, ()):
                continue
            diags.append(d)
    diags.extend(fingerprint_hygiene_diagnostics())
    return diags


def module_diagnostics(source: str, relpath: str,
                       rules: Tuple[str, ...] = ("TPU-R015", "TPU-R016")
                       ) -> List[Diagnostic]:
    """Run the R015/R016 visitors against one synthetic source (test
    fixtures, the --dsan anti-vacuity injections)."""
    tree = ast.parse(source, filename=relpath)
    diags: List[Diagnostic] = []
    if "TPU-R015" in rules:
        v = _VolatileSourceVisitor(relpath)
        v.visit(tree)
        diags.extend(v.diags)
    if "TPU-R016" in rules:
        fv = _ArrivalFoldVisitor(relpath)
        fv.visit(tree)
        diags.extend(fv.diags)
    allowed = _allowed_lines_of(source)
    out = []
    for d in diags:
        lineno = int(d.loc.rsplit(":", 1)[-1]) if ":" in d.loc else -1
        if lineno in allowed.get(d.code, ()):
            continue
        out.append(d)
    return out


def _allowed_lines_of(source: str) -> dict:
    from .repo_lint import _allowed_lines
    return _allowed_lines(source)


# ---------------------------------------------------------------------------
# repo-level artifact (tools lint --determinism)
# ---------------------------------------------------------------------------

def determinism_artifact() -> dict:
    """Declared replay classes for every registered operator class plus
    the fingerprint-hygiene status — the tpudsan analog of the raise
    graph / lock graph artifacts.  Class-level: operators whose
    declaration depends on instance state (aggregates) report
    'dynamic'."""
    import importlib
    import inspect

    from ..exec.base import Exec
    decls: Dict[str, str] = {}
    mods = ("spark_rapids_tpu.exec.base", "spark_rapids_tpu.exec.basic",
            "spark_rapids_tpu.exec.aggregate", "spark_rapids_tpu.exec.sort",
            "spark_rapids_tpu.exec.join", "spark_rapids_tpu.exec.window",
            "spark_rapids_tpu.exec.broadcast", "spark_rapids_tpu.exec.concat",
            "spark_rapids_tpu.exec.expand", "spark_rapids_tpu.exec.gatherpart",
            "spark_rapids_tpu.exec.outofcore",
            "spark_rapids_tpu.exec.pandas_udf",
            "spark_rapids_tpu.exec.python_udf",
            "spark_rapids_tpu.shuffle.exchange", "spark_rapids_tpu.shuffle.aqe",
            "spark_rapids_tpu.parallel.ici_exec",
            "spark_rapids_tpu.io.cached_batch", "spark_rapids_tpu.io.scan")
    for m in mods:
        mod = importlib.import_module(m)
        for name, cls in sorted(vars(mod).items()):
            if not (inspect.isclass(cls) and issubclass(cls, Exec) and
                    cls.__module__ == m) or name.startswith("_"):
                continue
            own = cls.determinism is not Exec.determinism
            if not own:
                decls[name] = f"{BIT_EXACT} (inherited default)"
                continue
            try:
                d = cls.determinism(_ClassProbe(cls))
                decls[name] = d.cls if d is not None else BIT_EXACT
            except Exception:
                decls[name] = "dynamic (instance-dependent)"
    hygiene = fingerprint_hygiene_diagnostics()
    return {
        "lattice": list(CLASSES),
        "declarations": decls,
        "fingerprint_hygiene": [d.render() for d in hygiene],
        "rules": {c: {"severity": r.severity, "title": r.title}
                  for c, r in (("TPU-L016", L016), ("TPU-L017", L017),
                               ("TPU-R015", R015), ("TPU-R016", R016))},
    }


class _ClassProbe:
    """Minimal instance stand-in so class-level declarations that only
    read class attributes can be probed without constructing the
    operator; anything touching instance state raises and reports
    'dynamic'."""

    def __init__(self, cls):
        self._cls = cls

    def __getattr__(self, name):
        v = getattr(self._cls, name, None)
        if v is None or callable(v):
            raise AttributeError(name)
        return v
