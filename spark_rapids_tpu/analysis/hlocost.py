"""tpuxsan cost model: the analytic roofline for compiled programs.

The reference's L4 speed comes from hand-written kernels; ours composes
generic XLA ops.  Before anyone writes a Pallas kernel we need to say
*where* the generic composition loses — and a ranked answer needs three
numbers per program:

* **analytic bytes** — what the program *should* move: a roofline built
  from the ledger's capacity-bucket signatures and dtype widths times a
  per-exec-kind pass count (how many capacity-sized sweeps the operator
  family's composition makes).  This is deliberately an
  order-of-magnitude model: it mirrors XLA's cost_analysis() convention
  (every op books operands + results) closely enough to cross-validate
  within ``spark.rapids.tpu.xsan.costTolerance``, and a model that
  drifts past the tolerance on the golden corpus FAILS the --hlo gate —
  a lying cost model is worse than none (the tmsan anti-vacuity
  discipline, applied to costing).
* **speed-of-light bytes** — what the operator's *semantics* require:
  one read plus one write of the LIVE data.  The ratio XLA-bytes /
  speed-of-light is the kernel gap a hand-written (Pallas) kernel could
  close.
* **padding waste** — the fraction of every launch that is bucket
  padding (live rows vs capacity), booked at runtime as
  ``tpu_pad_waste_bytes_total{exec}`` (obs/tracer.py) and estimated
  statically here for the TPU-L018 plan rule.

All three are pure functions of ledger records / interp states — no
device, no JAX import — so the audit runs in CI on a cold checkout.
"""

from __future__ import annotations

import math
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# ---------------------------------------------------------------------------
# dtype widths (ledger dtype strings are numpy/jax names)
# ---------------------------------------------------------------------------

_BITS = re.compile(r"(\d+)$")


def dtype_width(name: str) -> int:
    """Bytes per element of one ledger dtype string ('int64' -> 8,
    'float32' -> 4, 'bool' -> 1).  Unknown names cost 4 (the honest
    middle: flat lanes are int32/float32-dominated)."""
    if name in ("bool", "bool_", "int8", "uint8"):
        return 1
    m = _BITS.search(name or "")
    if m:
        return max(1, int(m.group(1)) // 8)
    return 4


def record_base_bytes(rec: Dict) -> int:
    """One capacity-sized sweep over a build record's input arrays:
    sum(prod(shape) * width(dtype)) over the dispatch-key leaves."""
    caps = rec.get("caps") or []
    dtypes = rec.get("dtypes") or []
    total = 0
    for i, shape in enumerate(caps):
        n = 1
        for d in shape:
            if isinstance(d, int):
                n *= max(d, 1)
        w = dtype_width(dtypes[i] if i < len(dtypes) else "")
        total += n * w
    return total


# ---------------------------------------------------------------------------
# the per-exec-kind pass model
# ---------------------------------------------------------------------------
# How many capacity-sized sweeps each operator family's generic-XLA
# composition makes, in cost_analysis() convention (a fused op books its
# operands AND its result, so even a pure elementwise map costs ~2-3x
# the data; a lax.sort books every operand on both sides plus the
# internal permutation traffic).  Calibrated against CPU-backend
# cost_analysis over the golden corpus (devtools/run_lint.py --hlo);
# the gate re-validates the calibration on every run.

KIND_PASSES: Dict[str, float] = {
    # elementwise map + compaction sort on the keep flag
    "FilterExec": 5.0,
    # elementwise expression evaluation, read + write
    "ProjectExec": 3.0,
    # multi-operand carry sort + segment reduce + group compaction
    "TpuHashAggregateExec": 8.0,
    # hash both sides, lexicographic sort, gather both payloads
    "HashJoinExec": 8.0,
    # key-word extraction + multi-operand stable sort + row gather
    "SortExec": 6.0,
    # partition sort + segmented scans over every frame function
    "WindowExec": 8.0,
}
DEFAULT_PASSES = 3.0

# The scan-composed families are NOT linear in the bucket: their
# programs chain associative scans and multi-operand sorts whose XLA
# lowering expands to log2(n) full-width stages each (cost_analysis
# books ~5-12x base PER log2(n) on the golden corpus, vs the flat
# 1.5-10x of the elementwise families above).  For these kinds the
# pass count is `coeff * log2(max bucket dim)`; the flat KIND_PASSES
# entry remains the memory-bound-family marker (TPU-L020) and the
# small-n floor.
LOG_PASS_KINDS: Dict[str, float] = {
    "TpuHashAggregateExec": 8.0,
    "HashJoinExec": 8.0,
    "WindowExec": 8.0,
}


def record_max_dim(rec: Dict) -> int:
    """Largest static dimension across a build record's dispatch-key
    leaves — the bucket the scan depth scales with."""
    n = 1
    for shape in rec.get("caps") or []:
        for d in shape:
            if isinstance(d, int) and d > n:
                n = d
    return n


def analytic_bytes(rec: Dict) -> int:
    """The roofline model's bytes-accessed for one ledger build record:
    base input bytes times the exec family's pass count (log-linear in
    the bucket for the scan-composed families)."""
    kind = rec.get("exec", "")
    passes = KIND_PASSES.get(kind, DEFAULT_PASSES)
    if kind in LOG_PASS_KINDS:
        depth = math.log2(max(record_max_dim(rec), 2))
        passes = max(passes, LOG_PASS_KINDS[kind] * depth)
    return int(record_base_bytes(rec) * passes)


def xla_bytes(rec: Dict) -> Optional[float]:
    """XLA's own bytes-accessed for a build record, or None when the
    backend did not report the key (absent is absent, never zero)."""
    cost = rec.get("cost")
    if not isinstance(cost, dict):
        return None
    v = cost.get("bytes accessed")
    return None if v is None else float(v)


def cost_agreement(rec: Dict, tolerance: float
                   ) -> Optional[Tuple[bool, float]]:
    """Cross-validate the analytic model against cost_analysis() for
    one record.  Returns (within_tolerance, ratio analytic/xla), or
    None when XLA reported no bytes (the record joins neither side of
    the >= 90% agreement bar)."""
    xb = xla_bytes(rec)
    if xb is None or xb <= 0:
        return None
    ratio = analytic_bytes(rec) / xb
    return (1.0 / tolerance) <= ratio <= tolerance, ratio


def validate_model(records: Iterable[Dict], tolerance: float) -> Dict:
    """The --hlo gate's model check over a ledger: every build record
    with an XLA bytes-accessed figure votes; the model passes when
    >= 90% of votes agree within the declared tolerance."""
    checked = agreed = 0
    worst: Optional[Tuple[float, Dict]] = None
    for rec in records:
        if rec.get("event") != "build":
            continue
        res = cost_agreement(rec, tolerance)
        if res is None:
            continue
        ok, ratio = res
        checked += 1
        agreed += 1 if ok else 0
        off = max(ratio, 1.0 / ratio) if ratio > 0 else float("inf")
        if worst is None or off > worst[0]:
            worst = (off, {"exec": rec.get("exec"),
                           "key": rec.get("key"),
                           "ratio": round(ratio, 3)})
    return {
        "checked": checked,
        "agreed": agreed,
        "agreement_pct": (100.0 * agreed / checked) if checked else None,
        "tolerance": tolerance,
        "worst": worst[1] if worst else None,
    }


# ---------------------------------------------------------------------------
# speed of light + the kernel gap
# ---------------------------------------------------------------------------

def speed_of_light_bytes(live_bytes: float) -> float:
    """What the semantics require: read the live data once, write the
    live result once.  The floor every kernel gap is measured against."""
    return 2.0 * max(float(live_bytes), 1.0)


def kernel_gap(xla_bytes_accessed: float, live_bytes: float) -> float:
    """How many times more memory traffic the compiled program makes
    than a speed-of-light kernel over the live data (>= 1.0)."""
    return max(float(xla_bytes_accessed) /
               speed_of_light_bytes(live_bytes), 1.0)


def projected_savings_s(measured_s: float, gap: float,
                        pad_ratio: float) -> float:
    """Seconds a hand-written kernel over live (unpadded) data could
    save: the measured time minus its speed-of-light share, where the
    gap already folds in the padded traffic and `pad_ratio` credits the
    launch-grain waste a dynamic-shape kernel also erases."""
    gap = max(float(gap), 1.0)
    base = measured_s * (1.0 - 1.0 / gap)
    # padding the gap model didn't see (host-measured launches)
    extra = measured_s * (1.0 / gap) * min(max(pad_ratio, 0.0), 0.99)
    return base + extra


# ---------------------------------------------------------------------------
# static padding-waste model (the TPU-L018 input)
# ---------------------------------------------------------------------------

def pad_waste_for(rows: float, capacity: int, row_width: float
                  ) -> Tuple[float, int]:
    """(waste ratio, wasted bytes) for `rows` live rows launched at
    `capacity` with `row_width` bytes per row."""
    capacity = max(int(capacity), 1)
    live = min(max(float(rows), 0.0), float(capacity))
    ratio = 1.0 - live / capacity
    return ratio, int((capacity - live) * max(row_width, 1))


# Operators whose output batches KEEP the input batch's capacity: the
# filter compacts survivors to the front and shrinks num_rows only,
# and the projection rewrites columns in place.  Everything else
# (aggregate, join, sort, exchange) emits freshly-bucketed batches.
CAPACITY_PRESERVING = frozenset({"FilterExec", "ProjectExec"})


def plan_pad_waste(root, conf, infer_result) -> List[Dict]:
    """Static per-node padding-waste table for one plan: the interp's
    row estimates vs the capacity each node's batches actually launch
    at.  Capacity propagates bottom-up — a filter's output keeps its
    input bucket (compaction shrinks num_rows, never capacity), which
    is exactly the waste the TPU-L018 re-bucket repair erases.  Pure
    planning-time arithmetic — the runtime books the measured twin via
    obs/tracer.py."""
    from ..columnar.device import bucket_for
    from .absdomain import schema_width
    buckets = conf.capacity_buckets
    out: List[Dict] = []

    def walk(node, path) -> Optional[int]:
        """Returns the node's output-batch capacity estimate."""
        here = f"{path} > {node.name}" if path else node.name
        child_caps = [walk(c, here) for c in node.children]
        st = infer_result.states.get(id(node)) if infer_result else None
        rows = getattr(st, "rows", None) if st is not None else None
        if rows is None or rows <= 0:
            return None
        if (type(node).__name__ in CAPACITY_PRESERVING and child_caps
                and child_caps[0]):
            cap = max(child_caps[0], bucket_for(int(rows), buckets))
        else:
            cap = bucket_for(int(rows), buckets)
        width = schema_width(node.output_types)
        ratio, waste = pad_waste_for(rows, cap, width)
        out.append({"node": node, "path": here,
                    "rows": float(rows), "capacity": cap,
                    "row_width": width, "waste_ratio": ratio,
                    "waste_bytes": waste})
        return cap

    walk(root, "")
    return out
