"""Shared rule/diagnostic framework for the plan and repo linters.

Modeled on the reference's generated-docs discipline (TypeChecks.scala
SupportedOpsDocs): every rule registers itself with a stable code, a
severity, and documentation, and the catalog is the single source for
docsgen output (docs/lint_rules.md), suppression handling, and the two
lint front ends.

Diagnostic codes:
  TPU-Lxxx — plan lint (hazards in a physical plan about to execute)
  TPU-Rxxx — repo lint (codebase invariants over the package source)
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

# severities, orderable: ERROR > WARN > INFO
ERROR = "error"
WARN = "warn"
INFO = "info"
_SEV_ORDER = {ERROR: 2, WARN: 1, INFO: 0}


class Rule:
    """One registered lint rule: stable code + severity + docs.

    `check` signature differs per front end (plan rules receive a
    LintContext, repo rules a parsed module) — the catalog only cares
    that every diagnostic traces back to a documented code."""

    def __init__(self, code: str, severity: str, title: str, doc: str,
                 check: Optional[Callable] = None):
        if severity not in _SEV_ORDER:
            raise ValueError(f"unknown severity {severity!r}")
        self.code = code
        self.severity = severity
        self.title = title
        self.doc = " ".join(doc.split())
        self.check = check

    def diag(self, message: str, loc: str = "", node=None,
             severity: Optional[str] = None) -> "Diagnostic":
        return Diagnostic(self.code, severity or self.severity, message,
                          loc=loc, node=node)


RULE_CATALOG: Dict[str, Rule] = {}


def register_rule(code: str, severity: str, title: str, doc: str,
                  check: Optional[Callable] = None) -> Rule:
    if code in RULE_CATALOG:
        raise ValueError(f"duplicate lint rule code {code}")
    r = Rule(code, severity, title, doc, check)
    RULE_CATALOG[code] = r
    return r


class Diagnostic:
    """One structured finding.

    `loc` is human-oriented: an operator path like
    ``HashJoinExec > ShuffleExchangeExec`` for plan lint, ``path:line``
    for repo lint.  `node` (plan lint only) is the offending Exec so the
    pre-flight can downgrade exactly that subtree; it never participates
    in equality/fingerprints."""

    __slots__ = ("code", "severity", "message", "loc", "node")

    def __init__(self, code: str, severity: str, message: str,
                 loc: str = "", node=None):
        self.code = code
        self.severity = severity
        self.message = message
        self.loc = loc
        self.node = node

    @property
    def is_error(self) -> bool:
        return self.severity == ERROR

    def fingerprint(self) -> str:
        """Stable identity for baselining: no line numbers, no node ids —
        a reshuffled file keeps its fingerprints."""
        path = self.loc.split(":", 1)[0]
        return f"{self.code}\t{path}\t{self.message}"

    def __repr__(self):
        return (f"Diagnostic({self.code}, {self.severity}, "
                f"{self.message!r}, loc={self.loc!r})")

    def render(self) -> str:
        where = f" [{self.loc}]" if self.loc else ""
        return f"{self.severity.upper():5s} {self.code}{where}: {self.message}"


def sort_diagnostics(diags: List[Diagnostic]) -> List[Diagnostic]:
    return sorted(diags, key=lambda d: (-_SEV_ORDER[d.severity], d.code,
                                        d.loc, d.message))


def format_diagnostics(diags: List[Diagnostic]) -> str:
    if not diags:
        return "no diagnostics\n"
    lines = [d.render() for d in sort_diagnostics(diags)]
    n_err = sum(1 for d in diags if d.severity == ERROR)
    n_warn = sum(1 for d in diags if d.severity == WARN)
    lines.append(f"{len(diags)} diagnostic(s): {n_err} error(s), "
                 f"{n_warn} warning(s)")
    return "\n".join(lines) + "\n"


def filter_suppressed(diags: List[Diagnostic],
                      disabled_codes) -> List[Diagnostic]:
    """Drop diagnostics whose code the user suppressed
    (spark.rapids.tpu.lint.disable, comma-separated)."""
    disabled = {c.strip() for c in disabled_codes if c.strip()}
    if not disabled:
        return diags
    return [d for d in diags if d.code not in disabled]
