"""Static plan/kernel analysis (`tpulint`).

The L8 tooling layer the reference ships as qualification/supported-ops/
api_validation reasons about plans *before* running them; this package is
the TPU-native extension of that idea to the correctness class round 5
surfaced: planning-time gates admitting plans the runtime then crashes
on, and plan shapes that defeat the JIT residency cache.

Two front ends share one rule/diagnostic framework (diagnostics.py):

  * plan lint (plan_lint.py)  — walks a converted physical plan and
    reports hazards as structured TPU-Lxxx diagnostics (error/warn/info);
    opt-in pre-flight via ``spark.rapids.tpu.lint.enabled`` downgrades
    hazardous subtrees to host fallback instead of crashing.
  * repo lint (repo_lint.py)  — AST pass over the package source
    enforcing codebase invariants as TPU-Rxxx diagnostics, with a
    checked-in baseline for pre-existing violations
    (devtools/lint_baseline.txt, devtools/run_lint.py).
  * tpucsan (concurrency.py)  — inter-procedural lock-order and
    shared-state concurrency sanitizer (TPU-R008/R009/R010); its
    static edge relation is the artifact the runtime lock witness
    (obs/lockwitness.py, spark.rapids.tpu.csan.enabled) validates
    against actual per-thread acquisition chains.

Both are driven by the machine-readable kernel capability table in
capabilities.py, which mirrors the actual dtype branch structure of the
kernels in ``parallel/`` and cross-checks every planning-time admission
gate against it (``verify_gates``) — the check class that provably
catches the round-5 alltoall admit/crash mismatch.

CLI: ``python -m spark_rapids_tpu.tools lint [--plan FIXTURE...|--repo]``.
"""

from .diagnostics import (ERROR, INFO, WARN, Diagnostic, Rule, RULE_CATALOG,
                          format_diagnostics, register_rule)
from .plan_lint import downgrade_hazards, lint_plan, lint_spark_plan
from .repo_lint import lint_repo, load_baseline
from .concurrency import (THREAD_ROOTS, analyze_repo, analyze_sources,
                          lock_order_artifact)

__all__ = [
    "Diagnostic", "Rule", "RULE_CATALOG", "ERROR", "WARN", "INFO",
    "format_diagnostics", "register_rule", "lint_plan", "lint_spark_plan",
    "downgrade_hazards", "lint_repo", "load_baseline",
    "THREAD_ROOTS", "analyze_repo", "analyze_sources",
    "lock_order_artifact",
]
