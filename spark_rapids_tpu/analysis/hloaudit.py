"""tpuxsan: compiled-program efficiency pass.

The compile observatory (obs/compileprof.py) already answers *when* we
compile and *what it costs in seconds*; nothing answered whether the
programs we compile are any good.  This pass closes that gap with three
static checks over the artifacts the observatory now persists — lowered
StableHLO text and XLA's own ``cost_analysis()`` per program — plus the
interp's row/byte states for the plan-side twin:

* **padding waste** (TPU-L018) — the capacity-bucket discipline that
  keeps compile counts finite also pads every launch; when the interp
  says a subtree's live rows are a sliver of the bucket it lands in,
  most of the memory traffic is padding.  Repairable: the pre-flight
  re-buckets the nearest filter through the existing speculative-sizing
  machinery (the guarded shrink re-executes on a missed guess, exactly
  like join speculation).
* **host round-trips inside programs** (TPU-L019) — a host callback or
  send/recv lowered INTO a compiled program serializes every launch on
  the host; found by parsing the persisted StableHLO, not by guessing
  from Python source.
* **fusion / materialization hazards** (TPU-L020) — adjacent
  memory-bound programs over a shared intermediate pay two sweeps where
  one fused kernel would pay none for the handoff; plus broadcasts that
  materialize above ``spark.rapids.tpu.xsan.broadcastBytesMax``.  These
  are the Pallas targets the kernel-gap report ranks.
* **kernel-table bypass** (TPU-R017) — a raw ``jnp.*``/``lax.*`` call
  in exec// ops/ outside a function registered in the device-kernel
  table (analysis/capabilities.py DEVICE_KERNELS) is a kernel the audit
  cannot see or cost; register it or annotate the deliberate exception.

The analytic cost model lives in analysis/hlocost.py; the --hlo gate
(devtools/run_lint.py) cross-validates it against cost_analysis() on
the golden corpus and fails on drift — a lying cost model is worse
than none.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

from .diagnostics import Diagnostic, ERROR, WARN, register_rule
from . import hlocost

# ---------------------------------------------------------------------------
# rule registrations
# ---------------------------------------------------------------------------

L018 = register_rule(
    "TPU-L018", ERROR, "launch padding dominates a subtree's traffic",
    "The interp's row estimate for a subtree is a sliver of the "
    "capacity bucket its launches pad to: the waste ratio exceeds "
    "spark.rapids.tpu.xsan.padWasteMax and the wasted bytes clear "
    "spark.rapids.tpu.xsan.padWasteMinBytes, so most of the memory "
    "traffic (and HBM residency) is padding.  Repairable: the "
    "pre-flight re-buckets the nearest filter speculatively — output "
    "shrinks to a right-sized bucket under a deferred guard, and a "
    "missed guess re-executes without speculation (the join "
    "speculative-sizing machinery).  The runtime twin is the "
    "tpu_pad_waste_bytes_total{exec} counter booked by obs/tracer.py.")

L019 = register_rule(
    "TPU-L019", ERROR, "host transfer inside a compiled program",
    "The persisted StableHLO for a compiled program contains a host "
    "callback custom_call or a send/recv on the result path: every "
    "launch of this program serializes on a device->host->device round "
    "trip, which defeats the async dispatch pipeline the engine is "
    "built around.  Found in the artifact XLA actually compiles, not "
    "inferred from Python source.  Hoist the host work out of the "
    "jitted function or replace the callback with a device kernel.")

L020 = register_rule(
    "TPU-L020", WARN, "fusion break between memory-bound programs",
    "Two adjacent memory-bound programs share an intermediate large "
    "enough that writing it out of one program and reading it back "
    "into the next costs more than either program's own arithmetic: a "
    "single fused kernel (the Pallas target list) would erase the "
    "handoff entirely.  Also flags a broadcast_in_dim that "
    "materializes above spark.rapids.tpu.xsan.broadcastBytesMax "
    "inside one program.  Advisory: these rank the kernel-gap report "
    "(tools kernel-report), they do not block a plan.")

R017 = register_rule(
    "TPU-R017", ERROR, "raw jnp/lax call bypasses the kernel table",
    "A jnp.* / lax.* call in exec/ or ops/ sits outside any function "
    "registered in the device-kernel table "
    "(analysis/capabilities.py DEVICE_KERNELS): the efficiency audit "
    "can neither cost nor gate a kernel it does not know exists, and "
    "the xp-parameterization convention (kernels take `xp` so the host "
    "path runs the same code on numpy) silently breaks.  Register the "
    "entry point or annotate the deliberate exception "
    "`# tpulint: allow[TPU-R017]` in place.  Dtype constructors "
    "(jnp.int64 and friends) and asarray are exempt — they carry no "
    "kernel semantics.")

# ---------------------------------------------------------------------------
# StableHLO text hazards (the artifact XLA actually compiles)
# ---------------------------------------------------------------------------

# `stablehlo.custom_call @target(...)` / `call_target_name = "target"`
_CUSTOM_CALL = re.compile(
    r"custom_call\s*@([\w.$-]+)|call_target_name\s*=\s*\"([^\"]+)\"")
_HOST_TARGET = re.compile(r"callback|host|infeed|outfeed", re.I)
_SEND_RECV = re.compile(r"\bstablehlo\.(send|recv)\b")
_BROADCAST = re.compile(r"broadcast_in_dim")
# result tensor types: `tensor<4000x8xi64>`, `tensor<f32>` (scalar)
_TENSOR = re.compile(r"tensor<([0-9]+(?:x[0-9]+)*x)?([a-z][a-z0-9]*)>")


def _elem_bytes(mlir_dtype: str) -> int:
    """Width of one MLIR element type name ('i64' -> 8, 'f32' -> 4,
    'i1' -> 1)."""
    m = re.search(r"(\d+)$", mlir_dtype)
    if not m:
        return 4
    return max(1, int(m.group(1)) // 8)


def _tensor_bytes(dims: Optional[str], dtype: str) -> int:
    n = 1
    for d in (dims or "").split("x"):
        if d.isdigit():
            n *= max(int(d), 1)
    return n * _elem_bytes(dtype)


def parse_hlo_hazards(text: str, broadcast_max: int) -> Dict[str, List]:
    """Line-oriented hazard scan over one persisted StableHLO module.

    Returns {"host_transfers": [(lineno, target)],
             "big_broadcasts": [(lineno, bytes)]}.  Pure text — no MLIR
    bindings required, so the audit runs on a cold CI checkout against
    artifacts recorded on any backend."""
    host: List[Tuple[int, str]] = []
    casts: List[Tuple[int, int]] = []
    for lineno, line in enumerate(text.splitlines(), start=1):
        m = _SEND_RECV.search(line)
        if m:
            host.append((lineno, f"stablehlo.{m.group(1)}"))
            continue
        if "custom_call" in line:
            cm = _CUSTOM_CALL.search(line)
            target = (cm.group(1) or cm.group(2)) if cm else ""
            if target and _HOST_TARGET.search(target):
                host.append((lineno, target))
            continue
        if _BROADCAST.search(line):
            # the result type is the LAST tensor type on the line
            # (`... -> tensor<...>`); operands come first
            types = _TENSOR.findall(line)
            if types:
                dims, dtype = types[-1]
                b = _tensor_bytes(dims, dtype)
                if b > broadcast_max:
                    casts.append((lineno, b))
    return {"host_transfers": host, "big_broadcasts": casts}


def audit_ledger(records: Iterable[Dict], hlo_dir: Optional[str],
                 broadcast_max: int) -> List[Diagnostic]:
    """TPU-L019 / TPU-L020(broadcast) over a compile ledger's persisted
    programs.  Records without a persisted artifact are skipped — the
    observatory caps and dedupes what it writes, and absence of an
    artifact is absence of evidence, never a clean bill."""
    diags: List[Diagnostic] = []
    if not hlo_dir or not os.path.isdir(hlo_dir):
        return diags
    seen: set = set()
    for rec in records:
        if rec.get("event") != "build":
            continue
        h = rec.get("hlo_hash")
        if not h or h in seen:
            continue
        seen.add(h)
        from ..obs.compileprof import HLO_SUFFIX
        path = os.path.join(hlo_dir, f"{h}{HLO_SUFFIX}")
        if not os.path.exists(path):
            continue
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        kind = rec.get("exec", "?")
        haz = parse_hlo_hazards(text, broadcast_max)
        for lineno, target in haz["host_transfers"]:
            diags.append(L019.diag(
                f"compiled {kind} program {h} lowers a host transfer "
                f"({target}) on its result path: every launch "
                f"serializes on the host round trip",
                loc=f"{kind}:{h}:{lineno}"))
        for lineno, nbytes in haz["big_broadcasts"]:
            diags.append(L020.diag(
                f"compiled {kind} program {h} materializes a "
                f"{nbytes / (1 << 20):.1f} MiB broadcast_in_dim "
                f"(budget {broadcast_max / (1 << 20):.0f} MiB): a "
                f"fused kernel would never write the expansion",
                loc=f"{kind}:{h}:{lineno}"))
    return diags


# ---------------------------------------------------------------------------
# plan-side audit (TPU-L018 padding waste, TPU-L020 fusion breaks)
# ---------------------------------------------------------------------------

def audit_plan(root, conf, infer_result) -> List[Diagnostic]:
    """Static efficiency rules over one converted plan, riding the
    interp states the pre-flight already computed.  Pure — the L018
    repair mutates only inside downgrade_hazards, like every other
    repairable rule."""
    from .. import config as cfg
    diags: List[Diagnostic] = []
    if infer_result is None:
        return diags

    max_ratio = conf.get(cfg.XSAN_PAD_WASTE_MAX)
    min_bytes = conf.get(cfg.XSAN_PAD_WASTE_MIN_BYTES)
    for w in hlocost.plan_pad_waste(root, conf, infer_result):
        if w["waste_ratio"] > max_ratio and w["waste_bytes"] >= min_bytes:
            diags.append(L018.diag(
                f"~{w['rows']:.0f} live rows pad to a "
                f"{w['capacity']}-row bucket: "
                f"{100 * w['waste_ratio']:.1f}% of the launch "
                f"(~{w['waste_bytes'] / (1 << 20):.1f} MiB/batch) is "
                f"padding traffic (budget {100 * max_ratio:.0f}%); "
                f"re-bucketing repairs this pre-flight",
                loc=w["path"], node=w["node"]))

    diags.extend(_fusion_breaks(root, conf, infer_result, min_bytes))
    return diags


def _fusion_breaks(root, conf, infer_result,
                   min_bytes: int) -> List[Diagnostic]:
    """TPU-L020: parent/child pairs of memory-bound device programs
    whose shared intermediate is large enough that the handoff (one
    write + one read of the intermediate) dominates either side's
    arithmetic — the cost model's fused estimate beats the sum."""
    from ..exec import base as eb
    from .absdomain import schema_width
    diags: List[Diagnostic] = []

    def walk(node, path):
        here = f"{path} > {node.name}" if path else node.name
        for c in node.children:
            pk = type(node).__name__
            ck = type(c).__name__
            if (pk in hlocost.KIND_PASSES and ck in hlocost.KIND_PASSES
                    and getattr(node, "placement", None) == eb.TPU
                    and getattr(c, "placement", None) == eb.TPU):
                st = infer_result.states.get(id(c))
                rows = getattr(st, "rows", None) if st is not None \
                    else None
                if rows and rows > 0:
                    inter = float(rows) * schema_width(c.output_types)
                    if inter >= min_bytes:
                        diags.append(L020.diag(
                            f"{ck} -> {pk} hand off a "
                            f"~{inter / (1 << 20):.1f} MiB intermediate "
                            f"between two memory-bound programs: a "
                            f"fused kernel saves "
                            f"~{2 * inter / (1 << 20):.1f} MiB of "
                            f"traffic per pass (kernel-gap report "
                            f"target)", loc=here, node=node))
            walk(c, here)

    walk(root, "")
    return diags


# ---------------------------------------------------------------------------
# the TPU-L018 repair: speculative re-bucketing
# ---------------------------------------------------------------------------

def try_rebucket_repair(root, node, conf) -> bool:
    """Arm the nearest FilterExec at-or-below the flagged subtree with a
    speculative output bucket sized from the interp's survivor
    estimate.  The filter then shrinks its compacted output to the
    right-sized bucket under a deferred guard
    (ExecContext.add_spec_guard); an undershoot raises
    SpeculativeSizingMiss and the session re-executes with speculation
    disabled — results built on a missed guess are never surfaced.
    Returns True when a repair was armed."""
    from ..columnar.device import bucket_for
    from ..exec.basic import FilterExec

    target = None
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, FilterExec):
            target = n
            break
        stack.extend(n.children)
    if target is None:
        return False

    from .interp import infer_plan
    states = infer_plan(root, conf).states
    st = states.get(id(target))
    rows = getattr(st, "rows", None) if st is not None else None
    if not rows or rows <= 0:
        return False
    # 1.5x headroom over the estimate: estimates are calibrated, not
    # exact, and a re-execution costs far more than half a bucket
    cap = bucket_for(max(int(rows * 1.5), int(rows) + 1),
                     conf.capacity_buckets)
    child_st = states.get(id(target.children[0]))
    in_rows = getattr(child_st, "rows", None) \
        if child_st is not None else None
    if in_rows and in_rows > 0:
        in_cap = bucket_for(int(in_rows), conf.capacity_buckets)
        if cap >= in_cap:
            return False  # no shrink: the repair would be a no-op
    target.rebucket_cap = int(cap)
    return True


# ---------------------------------------------------------------------------
# TPU-R017: raw jnp/lax calls outside the kernel table
# ---------------------------------------------------------------------------

_R017_PATHS = ("exec/", "ops/")
# dtype constructors / wrappers carry no kernel semantics
_BENIGN_TAILS = {
    "int8", "int16", "int32", "int64", "uint8", "uint16", "uint32",
    "uint64", "float16", "float32", "float64", "bool_", "asarray",
    "dtype", "ndarray", "issubdtype",
}


def _func_chain(f) -> List[str]:
    parts: List[str] = []
    while isinstance(f, ast.Attribute):
        parts.append(f.attr)
        f = f.value
    if isinstance(f, ast.Name):
        parts.append(f.id)
    return list(reversed(parts))


class _RawXlaCallVisitor:
    """TPU-R017 over one module (scope tracking via repo_lint's
    _ScopedVisitor, shared with every other repo rule)."""

    def __init__(self, relpath: str):
        from .capabilities import device_kernel_functions
        from .repo_lint import _ScopedVisitor
        outer = self

        class V(_ScopedVisitor):
            def visit_Call(self, node):
                outer._call(node, self.scope)
                self.generic_visit(node)

        self.relpath = relpath
        self._registered = device_kernel_functions(relpath)
        self.diags: List[Diagnostic] = []
        self._v = V()

    def visit(self, tree):
        self._v.visit(tree)

    def _call(self, node, scope: str):
        chain = _func_chain(node.func)
        if len(chain) < 2:
            return
        head = chain[0]
        if head == "jax" and len(chain) >= 3 and chain[1] in ("lax",
                                                              "numpy"):
            head, chain = chain[1], chain[1:]
        if head not in ("jnp", "lax"):
            return
        tail = chain[-1]
        if tail in _BENIGN_TAILS:
            return
        # nested helpers inside a registered kernel entry point pass:
        # the table registers the public surface, not every closure
        top = scope.split(".", 1)[0]
        if top in self._registered:
            return
        self.diags.append(R017.diag(
            f"raw {'.'.join(chain)}() in {scope} bypasses the kernel "
            f"table: register the entry point in "
            f"analysis/capabilities.py DEVICE_KERNELS or annotate the "
            f"deliberate exception", loc=f"{self.relpath}:{node.lineno}"))


def repo_diagnostics(root: Optional[str] = None) -> List[Diagnostic]:
    """TPU-R017 over exec/ and ops/; appended to lint_repo like the
    tpucsan/tpufsan/tpudsan passes."""
    from .repo_lint import _allowed_lines, _package_root, _py_files
    root = root or _package_root()
    diags: List[Diagnostic] = []
    for path in _py_files(root):
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        if not any(relpath.startswith(p) for p in _R017_PATHS):
            continue
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=relpath)
        except SyntaxError:
            continue  # TPU-R000 already reported by the core pass
        v = _RawXlaCallVisitor(relpath)
        v.visit(tree)
        if not v.diags:
            continue
        allowed = _allowed_lines(source)
        for d in v.diags:
            lineno = int(d.loc.rsplit(":", 1)[-1]) if ":" in d.loc else -1
            if lineno in allowed.get(d.code, ()):
                continue
            diags.append(d)
    return diags


def module_diagnostics(source: str, relpath: str) -> List[Diagnostic]:
    """Run the R017 visitor against one synthetic source (test
    fixtures, the --hlo anti-vacuity injections)."""
    from .repo_lint import _allowed_lines
    if not any(relpath.startswith(p) for p in _R017_PATHS):
        return []
    tree = ast.parse(source, filename=relpath)
    v = _RawXlaCallVisitor(relpath)
    v.visit(tree)
    allowed = _allowed_lines(source)
    out = []
    for d in v.diags:
        lineno = int(d.loc.rsplit(":", 1)[-1]) if ":" in d.loc else -1
        if lineno in allowed.get(d.code, ()):
            continue
        out.append(d)
    return out
