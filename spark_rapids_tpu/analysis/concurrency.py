"""tpucsan: lock-order & shared-state concurrency sanitizer.

The serving-era engine (PRs 8-12) holds 40+ ``threading.Lock`` /
``RLock`` / ``Condition`` instances across shuffle/, api/, memory/,
obs/ and native/, and nothing reasoned about how they compose — PR 9
already fixed a check-then-acquire race in the seed's TpuSemaphore by
hand.  This pass is the static half of the thread analogue of tmsan:

  TPU-R008  lock-order cycle: two code paths acquire the same pair of
            locks in opposite orders (potential ABBA deadlock).
  TPU-R009  shared mutable state (module global, class attribute, or
            instance attribute of a process-shared class) written from
            >= 2 declared thread roots with NO lock common to every
            write site.
  TPU-R010  condition-variable / raw-lock misuse: ``wait()`` outside a
            predicate re-check loop, ``notify()`` without the condition
            held, or an explicit ``acquire()`` with no ``finally``-path
            ``release()`` in the same function.

Pipeline (pure AST, no imports of the analyzed code):

  1. **Lock extraction** — every ``threading.Lock/RLock/Condition()``
     bound to a module global, a class attribute, or ``self.x`` gets a
     canonical name (``memory.admission.AdmissionController._cv``,
     ``obs.prewarm._save_lock``).  ``Event`` and ``Semaphore`` are NOT
     locks here (Event.wait is not a condvar wait; the UDF worker pool
     semaphore has its own cross-function pairing discipline).
  2. **Call graph** — conservative, type-aware-lite resolution:
     ``self.m()`` / ``cls.m()``, ``ClassName.m()``, constructor calls,
     singleton accessors (``return cls._instance`` classmethods),
     locals/attributes typed by construction, module-alias calls, and
     chained calls through inferred return types.  Receivers that stay
     unresolved fall back to name-based CHA (capped and blocklisted)
     for *reachability only* — never for lock-order edges, so an
     ambiguous name can hide a finding but cannot fabricate one.
  3. **Lock-order edges** — ``outer -> inner`` whenever ``inner`` is
     acquired (directly or via the typed may-acquire closure of a
     callee) while ``outer`` is held.  Per-instance locks of one class
     collapse onto one static node, so self-edges are dropped rather
     than reported as self-deadlock.
  4. **Thread roots** — the declared entry points concurrency actually
     starts from (``THREAD_ROOTS``): pool borrow, main query thread,
     transport fetch worker, block-server handler, heartbeat loop,
     prewarm background, metrics HTTP server.  Root reachability feeds
     TPU-R009; an always-held-locks fixpoint (intersection over call
     sites) supplies the inter-procedural guard set.

The edge relation is ONE shared artifact (``lock_order_artifact``):
the opt-in runtime witness (obs/lockwitness.py,
``spark.rapids.tpu.csan.enabled``) wraps the registered lock objects,
records actual per-thread acquisition chains, and fails when execution
observes an edge this pass missed or completes a cycle it flagged —
the static analysis is validated against execution, not just asserted.

Suppression: ``# tpulint: allow[TPU-R008]`` on a cycle lock's
declaration line, ``allow[TPU-R009]`` on any write site of the state,
``allow[TPU-R010]`` on the flagged wait/notify/acquire line.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from .diagnostics import Diagnostic, ERROR, WARN, register_rule

R008 = register_rule(
    "TPU-R008", ERROR, "lock-order cycle (potential ABBA deadlock)",
    "Two code paths acquire the same locks in opposite orders: if the "
    "paths ever interleave on different threads each can end up holding "
    "the lock the other is waiting for, and both block forever.  "
    "Establish one global acquisition order (or drop one nesting by "
    "copying state out under the first lock).  The runtime lock witness "
    "(spark.rapids.tpu.csan.enabled) fails hard if execution completes "
    "a flagged cycle.")

R009 = register_rule(
    "TPU-R009", ERROR, "shared state written from multiple thread roots "
    "without a common lock",
    "A module global, class attribute, or instance attribute of a "
    "process-shared class (singleton or lock-owning) is written from "
    ">= 2 declared thread entry points with no single lock held at "
    "every write: concurrent writers can interleave and lose updates "
    "(the GIL does not make += or dict writes atomic across the read-"
    "modify-write).  Guard every write site with one lock, or sanction "
    "a deliberately racy latch with tpulint: allow[TPU-R009].")

R010 = register_rule(
    "TPU-R010", ERROR, "condition-variable / raw-lock misuse",
    "wait() outside a while predicate loop misses spurious wakeups and "
    "stolen wakeups (the woken thread must re-check); notify() without "
    "the condition held raises at runtime or races the waiter's "
    "predicate read; an explicit acquire() whose release() is not on a "
    "finally path leaks the lock on any exception between them.  Use "
    "`with lock:` / `while not pred: cv.wait()` or pair acquire() with "
    "a try/finally release().")

# package-relative prefix every analyzed file carries
_PKG = "spark_rapids_tpu/"

_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "condition"}

# mutating container methods that count as writes for TPU-R009
_MUTATORS = {"append", "appendleft", "add", "update", "pop", "popleft",
             "popitem", "remove", "discard", "clear", "extend",
             "extendleft", "insert", "setdefault", "sort", "reverse"}

# CHA fallback (unresolved receivers, reachability only): skip names
# this generic — they would wire the whole repo together
_CHA_BLOCKLIST = {"get", "set", "inc", "dec", "close", "reset", "value",
                  "observe", "labels", "total", "series", "items",
                  "keys", "values", "start", "stop", "run", "read",
                  "write", "send", "recv", "join", "put", "copy",
                  "next", "flush", "release", "acquire", "wait",
                  "notify", "notify_all", "register", "unregister"}
_CHA_CAP = 6

# Declared thread entry points: (label, relpath suffix, scope suffix).
# These are where concurrency actually starts in this engine — the
# docs/static-analysis.md thread-root table is generated from this.
THREAD_ROOTS: Tuple[Tuple[str, str, str], ...] = (
    ("serving-client", "api/pool.py", "SessionPool.run"),
    ("main-query", "api/session.py", "TpuSession.execute"),
    ("shuffle-fetcher", "shuffle/transport.py",
     "AsyncBlockFetcher._producer"),
    ("block-server", "shuffle/transport.py", "Handler.handle"),
    ("heartbeat-loop", "shuffle/heartbeat.py", "HeartbeatEndpoint._run"),
    ("jit-prewarm", "obs/prewarm.py", "prewarm_from_ledger"),
    ("metrics-http", "obs/health.py", "do_GET"),
)


def _relmod(relpath: str) -> str:
    p = relpath
    if p.startswith(_PKG):
        p = p[len(_PKG):]
    if p.endswith(".py"):
        p = p[:-3]
    if p.endswith("/__init__"):
        p = p[:-len("/__init__")]
    return p.replace("/", ".")


class LockDef:
    __slots__ = ("name", "kind", "relpath", "lineno", "owner", "attr",
                 "class_level")

    def __init__(self, name, kind, relpath, lineno, owner, attr,
                 class_level):
        self.name = name          # canonical: relmod[.Class].attr
        self.kind = kind          # lock | rlock | condition
        self.relpath = relpath
        self.lineno = lineno
        self.owner = owner        # class key or None for module-level
        self.attr = attr
        self.class_level = class_level


class ClassInfo:
    __slots__ = ("key", "name", "relpath", "relmod", "methods",
                 "attr_types", "is_singleton", "lock_attrs")

    def __init__(self, key, name, relpath, relmod):
        self.key = key
        self.name = name
        self.relpath = relpath
        self.relmod = relmod
        self.methods: Dict[str, str] = {}      # method name -> fid
        self.attr_types: Dict[str, Set[Tuple[str, str]]] = {}
        self.is_singleton = False              # has an _instance attr
        self.lock_attrs: Dict[str, LockDef] = {}


class FuncInfo:
    __slots__ = ("fid", "relpath", "relmod", "scope", "node", "cls",
                 "is_init", "ret_types", "acquired", "direct_edges",
                 "callsites", "writes", "cv_events", "finally_released",
                 "local_funcs")

    def __init__(self, fid, relpath, relmod, scope, node, cls):
        self.fid = fid
        self.relpath = relpath
        self.relmod = relmod
        self.scope = scope        # dotted scope within the module
        self.node = node
        self.cls = cls            # enclosing ClassInfo key or None
        self.is_init = scope.split(".")[-1] in ("__init__", "__new__")
        self.ret_types: Set[Tuple[str, str]] = set()
        self.acquired: Set[str] = set()
        self.direct_edges: Set[Tuple[str, str]] = set()
        # (callee fids frozenset, via_cha, held frozenset, lineno)
        self.callsites: List[Tuple[FrozenSet[str], bool,
                                   FrozenSet[str], int]] = []
        # (state name, lineno, lexically held frozenset)
        self.writes: List[Tuple[str, int, FrozenSet[str]]] = []
        # (kind, lock name, lineno, held frozenset, loop_depth)
        self.cv_events: List[Tuple[str, str, int, FrozenSet[str], int]] = []
        self.finally_released: Set[str] = set()
        self.local_funcs: Dict[str, str] = {}  # nested def name -> fid


class Analysis:
    """The shared artifact: locks, edges, cycles, diagnostics."""

    def __init__(self):
        self.locks: Dict[str, LockDef] = {}
        self.edges: Set[Tuple[str, str]] = set()
        self.cycles: List[List[str]] = []
        self.diagnostics: List[Diagnostic] = []
        # diagnostic -> candidate (relpath, lineno) allow-annotation
        # sites (a cross-file finding can be sanctioned at any of them)
        self.allow_sites: Dict[int, List[Tuple[str, int]]] = {}
        self.roots: Dict[str, str] = {}        # root fid -> label
        self.reachable: Dict[str, Set[str]] = {}
        self.funcs: Dict[str, "FuncInfo"] = {}  # fid -> resolved info

    def artifact(self) -> Dict:
        """JSON-able lock-order relation the runtime witness consumes."""
        return {
            "locks": {n: d.kind for n, d in sorted(self.locks.items())},
            "edges": sorted(list(e) for e in self.edges),
            "cycles": [list(c) for c in self.cycles],
            "roots": dict(sorted(self.roots.items())),
        }


# ---------------------------------------------------------------------------
# pass 1: module structure (classes, methods, locks, imports, globals)
# ---------------------------------------------------------------------------

class _Module:
    __slots__ = ("relpath", "relmod", "tree", "source", "imports",
                 "globals", "funcs")

    def __init__(self, relpath, relmod, tree, source):
        self.relpath = relpath
        self.relmod = relmod
        self.tree = tree
        self.source = source
        # alias -> ("mod", relmod) | ("class", ckey) | ("func", fid)
        self.imports: Dict[str, Tuple[str, str]] = {}
        self.globals: Set[str] = set()
        self.funcs: List[str] = []


def _lock_ctor_kind(node) -> Optional[str]:
    """'lock'/'rlock'/'condition' when ``node`` is a direct
    threading.X() / X() construction (NOT e.g. type(threading.RLock()))."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id == "threading":
        return _LOCK_CTORS.get(f.attr)
    if isinstance(f, ast.Name):
        return _LOCK_CTORS.get(f.id)
    return None


class _Collector(ast.NodeVisitor):
    """Pass-1 walk of one module: classes, functions, locks, imports."""

    def __init__(self, an: "_Analyzer", mod: _Module):
        self.an = an
        self.mod = mod
        self.scope: List[str] = []
        self.cls_stack: List[Optional[ClassInfo]] = []
        self.func_stack: List[FuncInfo] = []

    # -- imports (module-wide, function-local included) ----------------------
    def visit_Import(self, node):
        for a in node.names:
            name = a.name
            alias = a.asname or name.split(".")[0]
            tgt = self.an.mod_by_dotted(name)
            if tgt is not None:
                self.mod.imports[alias] = ("mod", tgt)
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        base = self._resolve_from(node)
        if base is None:
            return
        for a in node.names:
            alias = a.asname or a.name
            sub = self.an.mod_by_dotted(f"{base}.{a.name}", relative=True)
            if sub is not None:
                self.mod.imports[alias] = ("mod", sub)
            else:
                self.mod.imports[alias] = ("sym", f"{base}:{a.name}")

    def _resolve_from(self, node) -> Optional[str]:
        if node.level == 0:
            return self.an.mod_by_dotted(node.module or "",
                                         want_pkg=True)
        parts = self.mod.relmod.split(".")
        # a module's package is its parents; __init__ already normalized
        parts = parts[:len(parts) - node.level] if node.level <= \
            len(parts) else []
        if node.module:
            parts += node.module.split(".")
        return ".".join(parts) if parts else None

    # -- classes / functions -------------------------------------------------
    def visit_ClassDef(self, node):
        key = f"{self.mod.relmod}." + ".".join(self.scope + [node.name])
        ci = ClassInfo(key, node.name, self.mod.relpath, self.mod.relmod)
        self.an.classes[key] = ci
        self.an.classes_by_name.setdefault(node.name, []).append(key)
        if not self.scope:
            self.mod.imports.setdefault(node.name, ("class", key))
        # class-level attrs: locks, _instance singleton marker
        for stmt in node.body:
            tgts, val = _assign_parts(stmt)
            for t in tgts:
                if not isinstance(t, ast.Name):
                    continue
                if t.id == "_instance":
                    ci.is_singleton = True
                kind = _lock_ctor_kind(val)
                if kind:
                    ld = LockDef(f"{key}.{t.id}", kind, self.mod.relpath,
                                 stmt.lineno, key, t.id, True)
                    self.an.locks[ld.name] = ld
                    ci.lock_attrs[t.id] = ld
        self.scope.append(node.name)
        self.cls_stack.append(ci)
        self.generic_visit(node)
        self.cls_stack.pop()
        self.scope.pop()

    def visit_FunctionDef(self, node):
        scope = ".".join(self.scope + [node.name])
        fid = f"{self.mod.relpath}::{scope}"
        cls = self.cls_stack[-1] if self.cls_stack else None
        fi = FuncInfo(fid, self.mod.relpath, self.mod.relmod, scope,
                      node, cls.key if cls else None)
        self.an.funcs[fid] = fi
        self.mod.funcs.append(fid)
        if cls is not None and len(self.scope) >= 1 and \
                self.scope[-1] == cls.name:
            cls.methods[node.name] = fid
        elif self.func_stack:
            self.func_stack[-1].local_funcs[node.name] = fid
        elif not self.scope:
            self.mod.imports.setdefault(node.name, ("lfunc", fid))
        # instance locks / attr types from `self.x = ...` in any method
        if cls is not None:
            for sub in ast.walk(node):
                tgts, val = _assign_parts(sub)
                for t in tgts:
                    if not (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        continue
                    kind = _lock_ctor_kind(val)
                    if kind and t.attr not in cls.lock_attrs:
                        ld = LockDef(f"{cls.key}.{t.attr}", kind,
                                     self.mod.relpath, sub.lineno,
                                     cls.key, t.attr, False)
                        self.an.locks[ld.name] = ld
                        cls.lock_attrs[t.attr] = ld
        self.scope.append(node.name)
        self.func_stack.append(fi)
        self.generic_visit(node)
        self.func_stack.pop()
        self.scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node):
        if not self.scope:  # module top level
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.mod.globals.add(t.id)
                    kind = _lock_ctor_kind(node.value)
                    if kind:
                        ld = LockDef(f"{self.mod.relmod}.{t.id}", kind,
                                     self.mod.relpath, node.lineno,
                                     None, t.id, False)
                        self.an.locks[ld.name] = ld
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if not self.scope and isinstance(node.target, ast.Name):
            self.mod.globals.add(node.target.id)
        self.generic_visit(node)


def _assign_parts(stmt):
    if isinstance(stmt, ast.Assign):
        return stmt.targets, stmt.value
    if isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        return [stmt.target], stmt.value
    return (), None


# ---------------------------------------------------------------------------
# pass 2: per-function scan with a lexical held-locks stack
# ---------------------------------------------------------------------------

class _FuncScan:
    """One function's linear walk: lock acquisitions (with/acquire),
    nested-call sites with the held set, shared-state writes, condvar
    events.  Statement order is preserved so explicit acquire/release
    pairs track the held set across siblings."""

    def __init__(self, an: "_Analyzer", fi: FuncInfo, mod: _Module):
        self.an = an
        self.fi = fi
        self.mod = mod
        self.held: List[str] = []
        self.loop_depth = 0
        self.declared_globals: Set[str] = set()
        # locals -> type tokens, seeded per scan round
        self.local_types: Dict[str, Set[Tuple[str, str]]] = {}

    def run(self):
        fi = self.fi
        fi.acquired.clear()
        fi.direct_edges.clear()
        fi.callsites = []
        fi.writes = []
        fi.cv_events = []
        fi.finally_released.clear()
        fi.ret_types = set()
        node = fi.node
        # prepass: local var types from simple assignments
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub is not node:
                continue
            tgts, val = _assign_parts(sub)
            for t in tgts:
                if isinstance(t, ast.Name) and val is not None:
                    ts = self.an.expr_types(val, self)
                    if ts:
                        self.local_types.setdefault(t.id, set()) \
                            .update(ts)
            if isinstance(sub, ast.Global):
                self.declared_globals.update(sub.names)
        for stmt in node.body:
            self._stmt(stmt)

    # -- statement dispatch --------------------------------------------------
    def _stmt(self, node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # separate scopes, scanned on their own
        if isinstance(node, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in node.items:
                lk = self.resolve_lock(item.context_expr)
                self._expr(item.context_expr)
                if lk is not None:
                    self._acquire(lk.name, node.lineno)
                    pushed += 1
            for s in node.body:
                self._stmt(s)
            for _ in range(pushed):
                self.held.pop()
            return
        if isinstance(node, ast.While):
            self._expr(node.test)
            self.loop_depth += 1
            for s in node.body:
                self._stmt(s)
            self.loop_depth -= 1
            for s in node.orelse:
                self._stmt(s)
            return
        if isinstance(node, ast.For):
            self._expr(node.iter)
            self.loop_depth += 1
            for s in node.body:
                self._stmt(s)
            self.loop_depth -= 1
            for s in node.orelse:
                self._stmt(s)
            return
        if isinstance(node, ast.Try):
            for lk in self.an.locks.values():
                pass
            for s in node.finalbody:
                for sub in ast.walk(s):
                    if isinstance(sub, ast.Call) and \
                            isinstance(sub.func, ast.Attribute) and \
                            sub.func.attr == "release":
                        lk = self.resolve_lock(sub.func.value)
                        if lk is not None:
                            self.fi.finally_released.add(lk.name)
            for s in (node.body + [h for hh in node.handlers
                                   for h in hh.body] + node.orelse +
                      node.finalbody):
                self._stmt(s)
            return
        if isinstance(node, (ast.If,)):
            self._expr(node.test)
            for s in node.body + node.orelse:
                self._stmt(s)
            return
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._record_write(node)
            for child in ast.iter_child_nodes(node):
                self._expr(child)
            return
        if isinstance(node, ast.Expr):
            self._expr(node.value)
            return
        if isinstance(node, ast.Return):
            if node.value is not None:
                self.fi.ret_types.update(
                    self.an.expr_types(node.value, self))
                self._expr(node.value)
            return
        if isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    st = self._state_of(t.value)
                    if st:
                        self.fi.writes.append(
                            (st, node.lineno, frozenset(self.held)))
            return
        # everything else: visit child statements/expressions generically
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._stmt(child)
            else:
                self._expr(child)

    # -- expressions: calls, lock-method events ------------------------------
    def _expr(self, node):
        if node is None or isinstance(node, (ast.FunctionDef,
                                             ast.AsyncFunctionDef,
                                             ast.ClassDef, ast.Lambda)):
            return
        if isinstance(node, ast.Call):
            self._call(node)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._stmt(child)
            else:
                self._expr(child)

    def _call(self, node: ast.Call):
        f = node.func
        handled = False
        if isinstance(f, ast.Attribute):
            lk = self.resolve_lock(f.value)
            if lk is not None and f.attr in (
                    "acquire", "release", "wait", "wait_for", "notify",
                    "notify_all", "locked"):
                handled = True
                held = frozenset(self.held)
                if f.attr == "acquire":
                    self.fi.cv_events.append(
                        ("acquire", lk.name, node.lineno, held,
                         self.loop_depth))
                    self._acquire(lk.name, node.lineno)
                elif f.attr == "release":
                    if lk.name in self.held:
                        self.held.remove(lk.name)
                elif f.attr == "wait":
                    self.fi.cv_events.append(
                        ("wait", lk.name, node.lineno, held,
                         self.loop_depth))
                elif f.attr in ("notify", "notify_all"):
                    self.fi.cv_events.append(
                        ("notify", lk.name, node.lineno, held,
                         self.loop_depth))
            # mutator method on shared state -> write
            if f.attr in _MUTATORS:
                st = self._state_of(f.value)
                if st:
                    self.fi.writes.append(
                        (st, node.lineno, frozenset(self.held)))
        if not handled:
            tgts, via_cha = self.an.resolve_call(node, self)
            if tgts:
                self.fi.callsites.append(
                    (frozenset(tgts), via_cha, frozenset(self.held),
                     node.lineno))
        for a in node.args:
            self._expr(a)
        for kw in node.keywords:
            self._expr(kw.value)
        if isinstance(f, ast.Attribute):
            self._expr(f.value)
        elif not isinstance(f, ast.Name):
            self._expr(f)

    def _acquire(self, name: str, lineno: int):
        for outer in self.held:
            if outer != name:
                self.fi.direct_edges.add((outer, name))
        self.fi.acquired.add(name)
        self.held.append(name)

    # -- writes --------------------------------------------------------------
    def _record_write(self, node):
        if isinstance(node, ast.Assign):
            targets, _ = node.targets, node.value
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:  # AugAssign
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Tuple):
                tt = list(t.elts)
            else:
                tt = [t]
            for tgt in tt:
                st = None
                if isinstance(tgt, ast.Subscript):
                    st = self._state_of(tgt.value)
                else:
                    st = self._state_of(tgt, store=True)
                if st:
                    self.fi.writes.append(
                        (st, node.lineno, frozenset(self.held)))

    def _state_of(self, expr, store: bool = False) -> Optional[str]:
        """Canonical shared-state name for a write target/receiver, or
        None when the receiver is local (untracked)."""
        fi = self.fi
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name):
            base = expr.value.id
            if base in ("self", "cls") and fi.cls is not None:
                if fi.is_init:
                    return None  # construction is single-threaded
                ci = self.an.classes[fi.cls]
                if base == "cls":
                    return f"{fi.cls}.{expr.attr}"  # class attr: shared
                if self.an.is_shared_class(ci):
                    if expr.attr in ci.lock_attrs:
                        return None  # rebinding a lock is its own sin
                    return f"{fi.cls}.{expr.attr}"
                return None
            tgt = self.mod.imports.get(base)
            if tgt and tgt[0] == "class":
                return f"{tgt[1]}.{expr.attr}"
            return None
        if isinstance(expr, ast.Name):
            name = expr.id
            if name in self.declared_globals or \
                    (not store and name in self.mod.globals):
                if (self.mod.relmod, name) in self.an.module_locks:
                    return None
                if store and name not in self.declared_globals:
                    return None
                return f"{self.mod.relmod}.{name}"
            return None
        return None

    # -- lock resolution -----------------------------------------------------
    def resolve_lock(self, expr) -> Optional[LockDef]:
        an = self.an
        if isinstance(expr, ast.Name):
            ld = an.module_locks.get((self.mod.relmod, expr.id))
            return ld
        if isinstance(expr, ast.Attribute):
            for tok in an.expr_types(expr.value, self):
                if tok[0] == "class":
                    ci = an.classes.get(tok[1])
                    if ci and expr.attr in ci.lock_attrs:
                        return ci.lock_attrs[expr.attr]
                elif tok[0] == "mod":
                    ld = an.module_locks.get((tok[1], expr.attr))
                    if ld:
                        return ld
        return None


# ---------------------------------------------------------------------------
# the analyzer
# ---------------------------------------------------------------------------

class _Analyzer:
    def __init__(self, sources: Dict[str, str],
                 roots: Optional[Iterable[str]] = None):
        self.sources = sources
        self.mods: Dict[str, _Module] = {}
        self.classes: Dict[str, ClassInfo] = {}
        self.classes_by_name: Dict[str, List[str]] = {}
        self.funcs: Dict[str, FuncInfo] = {}
        self.locks: Dict[str, LockDef] = {}
        self.module_locks: Dict[Tuple[str, str], LockDef] = {}
        self.methods_by_name: Dict[str, List[str]] = {}
        self.explicit_roots = list(roots) if roots is not None else None
        self._relmods: Set[str] = set()

    # -- module name resolution ----------------------------------------------
    def mod_by_dotted(self, dotted: str, relative: bool = False,
                      want_pkg: bool = False) -> Optional[str]:
        d = dotted
        if d.startswith("spark_rapids_tpu."):
            d = d[len("spark_rapids_tpu."):]
        elif d == "spark_rapids_tpu":
            d = ""
        if d in self._relmods:
            return d
        if want_pkg and (d == "" or any(
                r.startswith(d + ".") for r in self._relmods)):
            return d
        return None

    def is_shared_class(self, ci: ClassInfo) -> bool:
        """Instance state of these classes is treated as cross-thread
        shared: singletons, and classes that declare a lock (owning a
        lock is declaring yourself concurrent)."""
        return ci.is_singleton or bool(ci.lock_attrs)

    # -- type inference ------------------------------------------------------
    def expr_types(self, expr, scan: Optional[_FuncScan],
                   depth: int = 0) -> Set[Tuple[str, str]]:
        if depth > 6 or expr is None:
            return set()
        if isinstance(expr, ast.Name):
            name = expr.id
            if scan is not None:
                if name == "self" and scan.fi.cls:
                    return {("class", scan.fi.cls)}
                if name == "cls" and scan.fi.cls:
                    return {("class", scan.fi.cls)}
                lt = scan.local_types.get(name)
                if lt:
                    return set(lt)
                tgt = scan.mod.imports.get(name)
                if tgt:
                    if tgt[0] in ("mod", "class"):
                        return {tgt}
                    if tgt[0] == "sym":
                        m, sym = tgt[1].split(":")
                        ck = f"{m}.{sym}"
                        if ck in self.classes:
                            return {("class", ck)}
            return set()
        if isinstance(expr, ast.Attribute):
            # self._attr with a known construction type
            for tok in self.expr_types(expr.value, scan, depth + 1):
                if tok[0] == "class":
                    ci = self.classes.get(tok[1])
                    if ci:
                        at = ci.attr_types.get(expr.attr)
                        if at:
                            return set(at)
                        if expr.attr == "_instance" and ci.is_singleton:
                            return {("class", ci.key)}
                elif tok[0] == "mod" and scan is not None:
                    sub = self.mod_by_dotted(f"{tok[1]}.{expr.attr}")
                    if sub is not None:
                        return {("mod", sub)}
                    ck = f"{tok[1]}.{expr.attr}"
                    if ck in self.classes:
                        return {("class", ck)}
            return set()
        if isinstance(expr, ast.Call):
            tgts, _cha = self.resolve_call(expr, scan, typed_only=True)
            out: Set[Tuple[str, str]] = set()
            for t in tgts:
                if t.startswith("ctor:"):
                    out.add(("class", t[5:]))
                else:
                    fi = self.funcs.get(t)
                    if fi:
                        out.update(fi.ret_types)
            return out
        return set()

    # -- call resolution -----------------------------------------------------
    def resolve_call(self, node: ast.Call, scan: Optional[_FuncScan],
                     typed_only: bool = False
                     ) -> Tuple[Set[str], bool]:
        """Resolve a call to target fids.  Constructor targets carry a
        ``ctor:<classkey>`` pseudo-id alongside their __init__ fid.
        Returns (targets, resolved_via_cha)."""
        f = node.func
        out: Set[str] = set()
        if isinstance(f, ast.Name):
            name = f.id
            if scan is not None:
                # nested function in an enclosing scope
                lf = scan.fi.local_funcs.get(name)
                if lf:
                    return {lf}, False
                tgt = scan.mod.imports.get(name)
                if tgt:
                    if tgt[0] == "lfunc":
                        return {tgt[1]}, False
                    if tgt[0] == "class":
                        return self._ctor_targets(tgt[1]), False
                    if tgt[0] == "sym":
                        m, sym = tgt[1].split(":")
                        ck = f"{m}.{sym}"
                        if ck in self.classes:
                            return self._ctor_targets(ck), False
                        mod = self.mods.get_by_relmod(m) if hasattr(
                            self.mods, "get_by_relmod") else None
                        fid = self._module_func(m, sym)
                        if fid:
                            return {fid}, False
            return set(), False
        if isinstance(f, ast.Attribute):
            attr = f.attr
            recv_types = self.expr_types(f.value, scan)
            for tok in recv_types:
                if tok[0] == "class":
                    ci = self.classes.get(tok[1])
                    if ci:
                        fid = ci.methods.get(attr)
                        if fid:
                            out.add(fid)
                elif tok[0] == "mod":
                    fid = self._module_func(tok[1], attr)
                    if fid:
                        out.add(fid)
            if out:
                return out, False
            if typed_only:
                return set(), False
            # CHA fallback for reachability: every class defining this
            # method name, capped and blocklisted
            if attr in _CHA_BLOCKLIST or attr.startswith("__"):
                return set(), False
            cands = self.methods_by_name.get(attr, ())
            if 0 < len(cands) <= _CHA_CAP:
                return set(cands), True
        return out, False

    def _ctor_targets(self, ckey: str) -> Set[str]:
        out = {f"ctor:{ckey}"}
        ci = self.classes.get(ckey)
        if ci:
            init = ci.methods.get("__init__")
            if init:
                out.add(init)
        return out

    def _module_func(self, relmod: str, name: str) -> Optional[str]:
        mod = self._mods_by_relmod.get(relmod)
        if mod is None:
            return None
        tgt = mod.imports.get(name)
        if tgt and tgt[0] == "lfunc":
            return tgt[1]
        if tgt and tgt[0] == "class":
            return None
        return None

    # -- driver --------------------------------------------------------------
    def run(self) -> Analysis:
        # parse
        for relpath in sorted(self.sources):
            relmod = _relmod(relpath)
            self._relmods.add(relmod)
        self._mods_by_relmod: Dict[str, _Module] = {}
        trees = {}
        for relpath in sorted(self.sources):
            try:
                tree = ast.parse(self.sources[relpath],
                                 filename=relpath)
            except SyntaxError:
                continue
            trees[relpath] = tree
        for relpath, tree in trees.items():
            mod = _Module(relpath, _relmod(relpath), tree,
                          self.sources[relpath])
            self.mods[relpath] = mod
            self._mods_by_relmod[mod.relmod] = mod
        # pass 1
        for mod in self.mods.values():
            _Collector(self, mod).visit(mod.tree)
        for ld in self.locks.values():
            if ld.owner is None:
                self.module_locks[(_relmod(ld.relpath), ld.attr)] = ld
        for ci in self.classes.values():
            for mname, fid in ci.methods.items():
                self.methods_by_name.setdefault(mname, []).append(fid)
        # attr types (needs the class table, so after pass 1)
        self._collect_attr_types()
        # pass 2, three rounds so chained return types stabilize
        for _ in range(3):
            for fid in sorted(self.funcs):
                fi = self.funcs[fid]
                mod = self.mods.get(fi.relpath)
                if mod is not None:
                    _FuncScan(self, fi, mod).run()
        return self._finish()

    def _collect_attr_types(self):
        for fid in sorted(self.funcs):
            fi = self.funcs[fid]
            if fi.cls is None:
                continue
            ci = self.classes[fi.cls]
            mod = self.mods.get(fi.relpath)
            if mod is None:
                continue
            scan = _FuncScan(self, fi, mod)
            for sub in ast.walk(fi.node):
                tgts, val = _assign_parts(sub)
                for t in tgts:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id in ("self", "cls") and \
                            isinstance(val, ast.Call):
                        ts = self.expr_types(val, scan)
                        cts = {x for x in ts}
                        if cts:
                            ci.attr_types.setdefault(t.attr, set()) \
                                .update(cts)

    # -- fixpoints and rule emission -----------------------------------------
    def _finish(self) -> Analysis:
        res = Analysis()
        res.locks = dict(self.locks)

        # typed call graph (lock-edge propagation) and full graph
        typed: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}
        full: Dict[str, List[Tuple[str, FrozenSet[str]]]] = {}
        for fid, fi in self.funcs.items():
            tl, fl = [], []
            for tgts, via_cha, held, _ln in fi.callsites:
                for t in tgts:
                    if t.startswith("ctor:"):
                        continue
                    fl.append((t, held))
                    if not via_cha:
                        tl.append((t, held))
            typed[fid] = tl
            full[fid] = fl

        # may-acquire closure over the typed graph
        ma: Dict[str, Set[str]] = {fid: set(fi.acquired)
                                   for fid, fi in self.funcs.items()}
        changed = True
        while changed:
            changed = False
            for fid in self.funcs:
                cur = ma[fid]
                before = len(cur)
                for callee, _held in typed[fid]:
                    if callee in ma:
                        cur |= ma[callee]
                if len(cur) != before:
                    changed = True

        # lock-order edges: direct nesting + held-at-callsite x callee
        # closure (self-edges dropped: per-instance locks of one class
        # collapse onto one static node)
        edges: Set[Tuple[str, str]] = set()
        for fid, fi in self.funcs.items():
            edges |= fi.direct_edges
            for callee, held in typed[fid]:
                for inner in ma.get(callee, ()):
                    for outer in held:
                        if outer != inner:
                            edges.add((outer, inner))
        res.edges = edges

        # roots
        roots: Dict[str, str] = {}
        if self.explicit_roots is not None:
            for r in self.explicit_roots:
                for fid in self.funcs:
                    relpath, scope = fid.split("::", 1)
                    dotted = f"{_relmod(relpath)}.{scope}"
                    if dotted == r or dotted.endswith("." + r) or \
                            scope == r:
                        roots[fid] = r
        else:
            for label, path_sfx, scope_sfx in THREAD_ROOTS:
                for fid, fi in self.funcs.items():
                    if fi.relpath.endswith(path_sfx) and (
                            fi.scope == scope_sfx or
                            fi.scope.endswith("." + scope_sfx)):
                        roots[fid] = label
        res.roots = roots

        # reachability per root over the full graph
        succ: Dict[str, Set[str]] = {
            fid: {c for c, _h in full[fid]} for fid in self.funcs}
        reach: Dict[str, Set[str]] = {}
        for root in roots:
            seen = {root}
            stack = [root]
            while stack:
                cur = stack.pop()
                for nxt in succ.get(cur, ()):
                    if nxt not in seen and nxt in self.funcs:
                        seen.add(nxt)
                        stack.append(nxt)
            reach[root] = seen
        res.reachable = reach
        # retain the resolved function table: the exception-flow pass
        # (raiseflow.py) propagates raise sets over this same call
        # graph instead of re-resolving targets
        res.funcs = self.funcs

        # always-held fixpoint H(f) over the full graph, from roots
        TOP = None
        H: Dict[str, Optional[FrozenSet[str]]] = {
            fid: TOP for fid in self.funcs}
        work = []
        for root in roots:
            H[root] = frozenset()
            work.append(root)
        while work:
            cur = work.pop()
            base = H[cur] or frozenset()
            for callee, held in full.get(cur, ()):
                if callee not in H:
                    continue
                new = base | held
                old = H[callee]
                merged = new if old is TOP else (old & new)
                if merged != old:
                    H[callee] = merged
                    work.append(callee)

        self._rule_r008(res)
        self._rule_r009(res, reach, roots, H)
        self._rule_r010(res, H)
        res.diagnostics.sort(key=lambda d: (d.code, d.loc, d.message))
        return res

    # -- TPU-R008 ------------------------------------------------------------
    def _rule_r008(self, res: Analysis):
        graph: Dict[str, Set[str]] = {}
        for a, b in res.edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        sccs = _tarjan(graph)
        for scc in sccs:
            if len(scc) < 2:
                continue
            cyc = _shortest_cycle(graph, sorted(scc))
            res.cycles.append(cyc)
            path = " -> ".join(cyc + [cyc[0]])
            d = R008.diag(
                f"lock-order cycle {path} (potential ABBA deadlock)",
                loc=self._lock_loc(cyc[0]))
            res.diagnostics.append(d)
            res.allow_sites[id(d)] = [
                (self.locks[n].relpath, self.locks[n].lineno)
                for n in cyc if n in self.locks]

    def _lock_loc(self, name: str) -> str:
        ld = self.locks.get(name)
        return f"{ld.relpath}:{ld.lineno}" if ld else ""

    # -- TPU-R009 ------------------------------------------------------------
    def _rule_r009(self, res, reach, roots, H):
        # state -> [(fid, lineno, guard set)]
        by_state: Dict[str, List[Tuple[str, int, FrozenSet[str]]]] = {}
        for fid, fi in self.funcs.items():
            for st, lineno, held in fi.writes:
                by_state.setdefault(st, []).append((fid, lineno, held))
        for st in sorted(by_state):
            writes = by_state[st]
            st_roots = sorted({roots[r] for r in roots
                               if any(w[0] in reach[r] for w in writes)})
            if len(st_roots) < 2:
                continue
            guards: Optional[FrozenSet[str]] = None
            sites: List[Tuple[str, int]] = []
            for fid, lineno, held in writes:
                if not any(fid in reach[r] for r in roots):
                    continue
                h = H.get(fid)
                g = held | (h if h is not None else frozenset())
                guards = g if guards is None else (guards & g)
                fi = self.funcs[fid]
                sites.append((fi.relpath, lineno))
            if guards is None or guards:
                continue
            sites.sort()
            d = R009.diag(
                f"shared state {st} written from {len(st_roots)} thread "
                f"roots ({', '.join(st_roots)}) with no common guarding "
                f"lock", loc=f"{sites[0][0]}:{sites[0][1]}")
            res.diagnostics.append(d)
            res.allow_sites[id(d)] = sites

    # -- TPU-R010 ------------------------------------------------------------
    def _rule_r010(self, res, H):
        for fid in sorted(self.funcs):
            fi = self.funcs[fid]
            loc_scope = fi.scope
            for kind, lock, lineno, held, loop_depth in fi.cv_events:
                ld = self.locks.get(lock)
                if kind == "wait":
                    if ld is not None and ld.kind != "condition":
                        continue  # Lock.wait does not exist; ignore
                    if loop_depth == 0:
                        d = R010.diag(
                            f"{lock}.wait() outside a while predicate "
                            f"loop in {loc_scope}: spurious/stolen "
                            f"wakeups skip the re-check",
                            loc=f"{fi.relpath}:{lineno}")
                        res.diagnostics.append(d)
                        res.allow_sites[id(d)] = [(fi.relpath, lineno)]
                elif kind == "notify":
                    h = H.get(fid)
                    if lock in held or (h is not None and lock in h):
                        continue
                    d = R010.diag(
                        f"{lock}.notify() without the condition held "
                        f"in {loc_scope}: raises RuntimeError or races "
                        f"the waiter's predicate read",
                        loc=f"{fi.relpath}:{lineno}")
                    res.diagnostics.append(d)
                    res.allow_sites[id(d)] = [(fi.relpath, lineno)]
                elif kind == "acquire":
                    if lock in fi.finally_released:
                        continue
                    d = R010.diag(
                        f"explicit {lock}.acquire() in {loc_scope} "
                        f"with no finally-path release() in the same "
                        f"function: any exception in between leaks the "
                        f"lock", loc=f"{fi.relpath}:{lineno}")
                    res.diagnostics.append(d)
                    res.allow_sites[id(d)] = [(fi.relpath, lineno)]


def _tarjan(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Iterative Tarjan SCC."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    for start in sorted(graph):
        if start in index:
            continue
        work = [(start, iter(sorted(graph.get(start, ()))))]
        index[start] = low[start] = counter[0]
        counter[0] += 1
        stack.append(start)
        on_stack.add(start)
        while work:
            node, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack.add(nxt)
                    work.append((nxt, iter(sorted(graph.get(nxt, ())))))
                    advanced = True
                    break
                elif nxt in on_stack:
                    low[node] = min(low[node], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                out.append(scc)
    return out


def _shortest_cycle(graph: Dict[str, Set[str]],
                    scc: List[str]) -> List[str]:
    """A representative cycle inside one SCC (BFS from its min node)."""
    start = scc[0]
    members = set(scc)
    prev: Dict[str, Optional[str]] = {start: None}
    from collections import deque
    q = deque([start])
    while q:
        cur = q.popleft()
        for nxt in sorted(graph.get(cur, ())):
            if nxt == start:
                path = [cur]
                while prev[path[-1]] is not None:
                    path.append(prev[path[-1]])
                return list(reversed(path))
            if nxt in members and nxt not in prev:
                prev[nxt] = cur
                q.append(nxt)
    return scc


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def analyze_sources(sources: Dict[str, str],
                    roots: Optional[Iterable[str]] = None) -> Analysis:
    """Run the full pass over in-memory sources (fixtures, tests).
    ``roots`` are dotted thread-entry names (``mod.Class.method``);
    None selects the declared ``THREAD_ROOTS`` table."""
    return _Analyzer(sources, roots=roots).run()


def _package_sources(root: Optional[str] = None) -> Dict[str, str]:
    from .repo_lint import _package_root, _py_files
    root = root or _package_root()
    out = {}
    for path in _py_files(root):
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            out[relpath] = f.read()
    return out


_REPO_CACHE: Dict[str, Analysis] = {}


def analyze_repo(root: Optional[str] = None,
                 refresh: bool = False) -> Analysis:
    """The repo-wide analysis (memoized per process: the witness and
    the lint front end share one run)."""
    from .repo_lint import _package_root
    key = os.path.abspath(root or _package_root())
    if refresh or key not in _REPO_CACHE:
        _REPO_CACHE[key] = _Analyzer(_package_sources(root)).run()
    return _REPO_CACHE[key]


def repo_diagnostics(root: Optional[str] = None) -> List[Diagnostic]:
    """TPU-R008/R009/R010 over the package, with ``tpulint: allow``
    annotations honored at any of a finding's candidate sites."""
    res = analyze_repo(root)
    return filter_allowed(res, _package_sources(root))


def filter_allowed(res: Analysis,
                   sources: Dict[str, str]) -> List[Diagnostic]:
    from .repo_lint import _allowed_lines
    allowed: Dict[str, Dict[str, Set[int]]] = {}
    for relpath, source in sources.items():
        al = _allowed_lines(source)
        if al:
            allowed[relpath] = al
    out = []
    for d in res.diagnostics:
        sites = res.allow_sites.get(id(d)) or []
        if not sites and ":" in d.loc:
            p, _, ln = d.loc.rpartition(":")
            sites = [(p, int(ln))]
        sanctioned = any(
            ln in allowed.get(p, {}).get(d.code, ())
            for p, ln in sites)
        if not sanctioned:
            out.append(d)
    return out


def lock_order_artifact(root: Optional[str] = None) -> Dict:
    """The shared static artifact the runtime lock witness validates
    against: {'locks': {name: kind}, 'edges': [[a, b], ...],
    'cycles': [...], 'roots': {...}}."""
    return analyze_repo(root).artifact()
