"""Differential oracle: the abstract interpreter checked against the
engine it models.

``capabilities.verify_gates()`` established the discipline for dtype
gates: a planning-time predicate is only trusted because a drift check
probes it against the kernel it guards.  This module applies the same
discipline to the plan typechecker itself — for every subtree of a
plan, execute it on the numpy/JAX-cpu backend and assert the
interpreter's predictions hold on the real batches:

  * **schema** — every yielded batch carries exactly the predicted
    column names and dtypes;
  * **residency** — predicted DEVICE subtrees yield jax-backed batches,
    predicted HOST subtrees numpy-backed ones;
  * **partition count** — a predicted count matches the node's actual
    partitioning;
  * **hash clustering** — a predicted ``HashDist(keys, n)`` is verified
    extensionally: the distinct key tuples observed in different
    partitions are pairwise disjoint;
  * **ordering** — a predicted within-partition sort contract is
    verified on the materialized rows.

``verify_plan`` runs over the golden good-plan corpus in
tests/test_interp_oracle.py and devtools/run_lint.py --interp: any
mismatch means the analyzer drifted from the engine and fails tier-1
(zero false rejects); the bad-plan fixtures keep the other direction
honest (zero false admits).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

from .. import config as cfg
from ..exec import base as eb
from .absdomain import (DEVICE, HOST, AbstractState, HashDist,
                        ReplicatedDist, SingleDist)


class Observation:
    """What one subtree's real execution showed."""

    __slots__ = ("names", "dtypes", "device", "partitions",
                 "partition_tables", "rows")

    def __init__(self, names, dtypes, device, partitions,
                 partition_tables, rows):
        self.names = names                      # per-batch column names
        self.dtypes = dtypes                    # per-batch column dtypes
        self.device = device                    # bool | None (no batches)
        self.partitions = partitions
        self.partition_tables = partition_tables  # pid -> list[RecordBatch]
        self.rows = rows


def _observe(node: eb.Exec, ctx: eb.ExecContext) -> Observation:
    from ..columnar.fetch import batch_is_device
    names: Optional[Tuple[str, ...]] = None
    dtypes: Optional[Tuple] = None
    device: Optional[bool] = None
    tables: Dict[int, list] = {}
    rows = 0
    nparts = node.num_partitions
    for pid in range(nparts):
        tables[pid] = []
        for b in node.execute_partition(pid, ctx):
            bn = tuple(b.names)
            bt = tuple(c.dtype for c in b.columns)
            if names is None:
                names, dtypes = bn, bt
            elif bn != names or tuple(map(repr, bt)) != \
                    tuple(map(repr, dtypes)):
                raise AssertionError(
                    f"{node.name} yields inconsistent batch schemas: "
                    f"{bn} vs {names}")
            device = bool(batch_is_device(b)) if device is None \
                else (device or batch_is_device(b))
            rb = eb.to_host_batch(b, b.names)
            rows += rb.num_rows
            if rb.num_rows:
                tables[pid].append(rb)
    return Observation(names, dtypes, device, nparts, tables, rows)


def _key_tuples(batches, names: Sequence[str],
                keys: Sequence[str]) -> Set[tuple]:
    out: Set[tuple] = set()
    idx = [list(names).index(k) for k in keys]
    for rb in batches:
        cols = [rb.column(i).to_pylist() for i in idx]
        out.update(zip(*cols) if cols else ())
    return out


def _check_ordering(batches, names: Sequence[str],
                    ordering) -> Optional[str]:
    """Verify the first ordering key is monotone over the partition's
    rows in yield order (nulls skipped — null placement is a separate
    contract the domain does not model)."""
    if not ordering:
        return None
    key, asc = ordering[0]
    if key not in names:
        return f"predicted ordering key {key!r} missing from output"
    i = list(names).index(key)
    vals = [v for rb in batches for v in rb.column(i).to_pylist()
            if v is not None]
    ok = all(a <= b for a, b in zip(vals, vals[1:])) if asc else \
        all(a >= b for a, b in zip(vals, vals[1:]))
    if not ok:
        return (f"predicted {'ascending' if asc else 'descending'} "
                f"ordering on {key!r} does not hold at runtime")
    return None


def _compare(st: AbstractState, obs: Observation) -> List[str]:
    out: List[str] = []
    if obs.names is not None:
        if tuple(st.names) != obs.names:
            out.append(f"predicted columns {list(st.names)} but execution "
                       f"produced {list(obs.names)}")
        elif [repr(dt) for dt in st.dtypes] != \
                [repr(dt) for dt in obs.dtypes]:
            pred = [dt.name for dt in st.dtypes]
            got = [dt.name for dt in obs.dtypes]
            out.append(f"predicted dtypes {pred} but execution produced "
                       f"{got}")
    if obs.device is not None:
        pred_dev = st.residency == DEVICE
        if pred_dev != obs.device:
            out.append(f"predicted {st.residency} residency but batches "
                       f"are {'device' if obs.device else 'host'}-backed")
    if st.num_partitions is not None and \
            st.num_partitions != obs.partitions:
        out.append(f"predicted {st.num_partitions} partition(s) but the "
                   f"operator runs {obs.partitions}")
    if isinstance(st.dist, SingleDist) and obs.partitions != 1:
        out.append(f"predicted single-partition distribution but the "
                   f"operator runs {obs.partitions} partitions")
    if isinstance(st.dist, HashDist) and obs.names is not None and \
            all(k in obs.names for k in st.dist.keys):
        if st.dist.num_partitions is not None and \
                st.dist.num_partitions != obs.partitions:
            out.append(f"predicted hash routing over "
                       f"{st.dist.num_partitions} partitions but the "
                       f"operator runs {obs.partitions}")
        seen: Dict[tuple, int] = {}
        for pid, batches in obs.partition_tables.items():
            for kt in _key_tuples(batches, obs.names, st.dist.keys):
                prev = seen.setdefault(kt, pid)
                if prev != pid:
                    out.append(
                        f"predicted clustering on "
                        f"[{', '.join(st.dist.keys)}] is violated: key "
                        f"{kt} appears in partitions {prev} and {pid}")
                    break
    if obs.names is not None:
        for pid, batches in obs.partition_tables.items():
            err = _check_ordering(batches, obs.names, st.ordering)
            if err:
                out.append(f"partition {pid}: {err}")
                break
    return out


def verify_plan(root: eb.Exec, conf: cfg.RapidsConf,
                skip: Sequence[type] = ()) -> List[str]:
    """Execute every subtree of `root` on the numpy backend and return
    every way the interpreter's predictions disagree with reality
    (empty list = the analyzer matches the engine on this plan)."""
    from .interp import infer_plan
    result = infer_plan(root, conf)
    ctx = eb.ExecContext(conf)
    # speculative sizing defers correctness guards to the collect
    # boundary; the oracle reads interior nodes directly, so run exact
    ctx.task_context["no_speculation"] = True
    mismatches: List[str] = []

    def walk(node: eb.Exec, path: str):
        here = f"{path} > {node.name}" if path else node.name
        for c in node.children:
            walk(c, here)
        if skip and isinstance(node, tuple(skip)):
            return
        st = result.state(node)
        if st is None:
            return
        try:
            obs = _observe(node, ctx)
        except AssertionError as ex:
            mismatches.append(f"{here}: {ex}")
            return
        mismatches.extend(f"{here}: {m}" for m in _compare(st, obs))

    walk(root, "")
    return mismatches
