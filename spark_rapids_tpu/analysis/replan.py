"""Exchange-boundary re-planning: act on measured map-output sizes
BEFORE the reduce side launches.

A shuffle materializes its map stage the first time any reduce
partition is requested (shuffle/aqe.py), which means real per-partition
byte counts exist at exactly the point Spark's AQE re-plans between
query stages.  The coalesce/skew rules already consume them locally;
this module closes the loop for the three decisions that live ABOVE
the reader:

  * **strategy_switch** — the measured exchange output is off the
    predicted size by at least ``feedback.replan.misestimateFactor``
    (either direction): pin ``no_speculation`` on the query's execution
    context so the reduce-side join runs exact two-phase sizing instead
    of gambling on a capacity guess it would lose.  This supersedes the
    after-the-fact ``SpeculativeSizingMiss`` retry on this path — the
    misestimate is caught from the map statistics, not from a failed
    guard after the join already ran.
  * **oc_repair** — re-run the abstract interpreter over the plan with
    the exchange's row estimate overridden by the measured one; if the
    re-derived peak-HBM bound overshoots the admission budget, force
    the out-of-core repair (TPU-L014) on the repairable frontier now,
    while the reduce side is still unlaunched.
  * **ticket_reprice** — hand the sharpened bound to
    ``AdmissionController.reprice`` so the live ticket's reservation
    is truthful for the rest of the query.

Every decision is triple-sunk — a ``replan`` span in the flight
recorder, ``tpu_replan_total{decision,cause}`` in the metrics registry,
and a ``replan`` event in the estimator ledger — so the three surfaces
can be cross-checked (the --feedback CI gate does exactly that).

The context is installed thread-locally by the session around
``execute_collect``; partition iteration is driver-threaded, so the
reader's ``specs()`` call lands on the installing thread.  Everything
here is advisory: any failure degrades to the static plan, never the
query.
"""

from __future__ import annotations

import logging
import threading
from typing import List, Optional

from .. import config as cfg

log = logging.getLogger(__name__)

_tls = threading.local()


class ReplanContext:
    """One query's re-planning state: the plan being executed, its
    admission ticket, and the shuffles already considered (each
    exchange boundary is re-planned at most once per execution)."""

    __slots__ = ("plan_root", "conf", "ticket", "controller", "tracer",
                 "exec_ctx", "seen", "decisions")

    def __init__(self, plan_root, conf, ticket, controller, tracer,
                 exec_ctx):
        self.plan_root = plan_root
        self.conf = conf
        self.ticket = ticket
        self.controller = controller
        self.tracer = tracer
        self.exec_ctx = exec_ctx
        self.seen = set()
        self.decisions: List = []


def install(ctx: ReplanContext) -> None:
    _tls.ctx = ctx


def uninstall() -> None:
    _tls.ctx = None


def current() -> Optional[ReplanContext]:
    return getattr(_tls, "ctx", None)


def on_map_stage_materialized(read_node, shuffle_id: int,
                              sizes: List[int]) -> None:
    """The AQE reader's callback, right after ``partition_stats``
    measured the freshly written map output."""
    ctx = current()
    if ctx is None:
        return
    try:
        _replan(ctx, read_node, shuffle_id, sizes)
    except Exception:
        log.debug("exchange-boundary replan skipped", exc_info=True)


def scan_materialized(ctx: ReplanContext) -> None:
    """Replay boundaries that materialized BEFORE the context existed:
    plan surgery (overrides' transition insertion) queries the root's
    ``num_partitions``, which walks down to the probe-side AQE reader
    and forces its map stage at plan time — before admission has issued
    a ticket or the session could install this context.  The session
    calls this right after installing, still ahead of the first reduce
    partition, so those boundaries get the same treatment as ones that
    materialize mid-execution."""
    try:
        from ..shuffle.aqe import partition_stats

        def visit(node):
            if not (hasattr(node, "exchange")
                    and hasattr(node, "_specs")):
                return
            if getattr(node, "replicate_for", None) is not None:
                return  # mirrors its partner; no stats of its own
            sid = getattr(node.exchange, "_shuffle_id", None)
            if sid is None or sid in ctx.seen:
                return  # map stage not written yet: specs() will call
            sizes = partition_stats(sid, node.exchange.num_partitions)
            _replan(ctx, node, sid, sizes)

        ctx.plan_root.foreach(visit)
    except Exception:
        log.debug("replan scan skipped", exc_info=True)


def _replan(ctx: ReplanContext, read_node, shuffle_id: int,
            sizes: List[int]) -> None:
    conf = ctx.conf
    if not conf.get(cfg.FEEDBACK_ENABLED):
        return
    if shuffle_id in ctx.seen:
        return
    ctx.seen.add(shuffle_id)

    exchange = getattr(read_node, "exchange", None)
    preds = getattr(ctx.tracer, "predictions", {}) \
        if ctx.tracer is not None else {}
    pred = preds.get(id(exchange)) if exchange is not None else None
    measured_bytes = int(sum(sizes))
    measured_rows = _measured_rows(shuffle_id, len(sizes))
    pred_bytes = pred.get("bytes") if pred else None
    pred_rows = pred.get("rows") if pred else None

    # the misestimate factor keys on ROWS when both sides know them
    # (the row model is what feedback sharpens; byte totals can be
    # right for the wrong reasons), bytes otherwise
    factor = None
    if measured_rows is not None and pred_rows:
        factor = measured_rows / max(float(pred_rows), 1.0)
    elif pred_bytes:
        factor = measured_bytes / max(float(pred_bytes), 1.0)

    rf = conf.get(cfg.FEEDBACK_REPLAN_FACTOR)
    tripped = factor is not None and \
        (factor >= rf or factor <= 1.0 / rf)
    cause = "row_misestimate" if tripped else "sizing_update"

    def sink(decision: str, **extra) -> None:
        # triple sink: span + metric + ledger must always agree
        from ..obs.estimator import EstimatorLedger
        from ..obs.tracer import trace_span
        ctx.decisions.append((decision, cause))
        with trace_span("replan", kind="replan", decision=decision,
                        cause=cause, shuffle_id=shuffle_id,
                        measured_bytes=measured_bytes,
                        predicted_bytes=pred_bytes,
                        factor=None if factor is None
                        else round(factor, 4), **extra):
            pass
        EstimatorLedger.get().record_replan(
            decision, cause, shuffle_id=shuffle_id,
            measured_bytes=measured_bytes, predicted_bytes=pred_bytes,
            factor=None if factor is None else round(factor, 4),
            **extra)

    if tripped and ctx.exec_ctx is not None and \
            not ctx.exec_ctx.task_context.get("no_speculation"):
        # exact two-phase sizing for every operator still to run — the
        # reduce-side join shares this context
        ctx.exec_ctx.task_context["no_speculation"] = True
        sink("strategy_switch")

    if measured_rows is None or exchange is None or \
            ctx.ticket is None or ctx.controller is None:
        return
    overrides = {id(exchange): float(measured_rows)}
    bound = _rebound(ctx, conf, overrides)
    if bound is None:
        return
    if bound > ctx.controller.budget_bytes:
        if _oc_repair(ctx, overrides):
            sink("oc_repair", new_bound=bound)
            bound = _rebound(ctx, conf, overrides) or bound
    delta = ctx.controller.reprice(ctx.ticket, bound)
    if delta:
        sink("ticket_reprice", new_bound=int(bound), delta=delta)


def _measured_rows(shuffle_id: int, n_parts: int) -> Optional[int]:
    """Exact row count of the materialized map output, straight from
    the shuffle catalog's block metadata (same walk as
    ``partition_stats``, reading rows instead of bytes)."""
    try:
        from ..shuffle.manager import TpuShuffleManager
        mgr = TpuShuffleManager.get()
        total = 0
        for rid in range(n_parts):
            for blk in mgr.catalog.blocks_for_reduce(shuffle_id, rid):
                for b in mgr.catalog.get(blk):
                    total += getattr(b, "num_rows", 0) or 0
        return total
    except Exception:
        return None


def _rebound(ctx: ReplanContext, conf, overrides) -> Optional[int]:
    """The plan's peak-HBM bound with the measured exchange rows
    substituted into the abstract interpretation."""
    try:
        from .interp import infer_plan
        from .lifetime import analyze_memory
        interp = infer_plan(ctx.plan_root, conf,
                            row_overrides=overrides)
        mem = analyze_memory(ctx.plan_root, conf, interp)
        b = mem.bound(ctx.plan_root)
        return None if b is None else int(b)
    except Exception:
        return None


def _oc_repair(ctx: ReplanContext, overrides) -> bool:
    """Force out-of-core mode on the repairable frontier against the
    ADMISSION budget (mirrors the session's pre-admission repair, but
    driven by measured rows and run before the reduce side starts)."""
    try:
        from .interp import infer_plan
        from .lifetime import analyze_memory, try_outofcore_repair
        conf2 = ctx.conf.set(cfg.MEMSAN_HBM_BUDGET.key,
                             int(ctx.controller.budget_bytes))
        interp = infer_plan(ctx.plan_root, conf2,
                            row_overrides=overrides)
        res = analyze_memory(ctx.plan_root, conf2, interp)
        done = False
        for d in res.diags:
            if d.code == "TPU-L014" and d.node is not None:
                try:
                    done = try_outofcore_repair(
                        ctx.plan_root, d.node, conf2) or done
                except Exception:
                    pass  # unrepairable node: keep the honest bound
        return done
    except Exception:
        return False
