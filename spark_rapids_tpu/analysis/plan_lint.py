"""Plan lint: walk a converted physical plan before execution and report
hazards as structured TPU-Lxxx diagnostics.

The rule classes target what round 5 showed actually breaks queries:

  TPU-L001  planning gate admits dtypes a collective kernel raises on
            (the ICI ungrouped array/map aggregate admit/crash mismatch)
  TPU-L002  device<->host ping-pong: a host island inside a device pipe
  TPU-L003  expression admitted on a TPU-placed operator with no device
            lowering (would evaluate on host per batch, or fail)
  TPU-L004  driver-side whole-build collect above the size threshold
  TPU-L005  shape-bucket / schema churn that defeats the JIT residency
            cache (the round-5 multichip compile-churn killer)
  TPU-L006  partitioning/ordering contract consumed above a subtree
            whose establishing exchange was rewritten away
  TPU-L007  ICI transport silently staging an exchange through host
            Arrow because of its column types
  TPU-L008  opaque Python-UDF boundary inside a device pipeline

The flow-sensitive rules TPU-L009..L012 (schema mismatch at a boundary,
dead exchange columns, contract violation after rewrite, residency
ping-pong totals) live in ``analysis/interp.py`` — the abstract
interpreter whose per-subtree states also upgrade L002/L006/L007 here
from syntactic to flow-sensitive (see docs/static-analysis.md).

``lint_plan`` is pure analysis; ``downgrade_hazards`` applies the safe
repairs (host fallback by placement flip — the CPU engine runs the
identical xp-parameterized kernels) for the rules where that is sound,
which is what ``spark.rapids.tpu.lint.enabled`` wires into
plan/overrides.py as an opt-in pre-flight.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

from .. import config as cfg
from .. import types as t
from ..exec import base as eb
from .capabilities import ALLGATHER_BATCH, EXCHANGE_BY_PID
from .diagnostics import (ERROR, INFO, WARN, Diagnostic, filter_suppressed,
                          register_rule, sort_diagnostics)

# ---------------------------------------------------------------------------
# rule registrations (catalog entries feed docsgen + suppression)
# ---------------------------------------------------------------------------

L001 = register_rule(
    "TPU-L001", ERROR, "ICI admit/capability mismatch",
    "An ungrouped aggregate's partial buffers pass the exchange admission "
    "gate but contain types the allgather kernel raises "
    "NotImplementedError on; under spark.rapids.shuffle.transport=ici the "
    "plan would pass planning and crash mid-query.  Derived from the "
    "capability table (analysis/capabilities.py) mirroring "
    "parallel/alltoall.py's actual dtype branches.")

L002 = register_rule(
    "TPU-L002", WARN, "device-host ping-pong",
    "A CPU-placed operator sits between TPU-placed producer and consumer: "
    "every batch crosses the interconnect twice (tens of ms fixed latency "
    "each way on a tunneled TPU) for one host operator.")

L003 = register_rule(
    "TPU-L003", ERROR, "host-only expression on a device operator",
    "A TPU-placed operator carries an expression with no device lowering "
    "(unregistered, disabled, or tagged host-only, e.g. regex).  The "
    "overrides engine should have kept the operator on CPU; executing it "
    "on device would fail or silently ship rows to host per batch.")

L004 = register_rule(
    "TPU-L004", ERROR, "driver-side whole-build collect above threshold",
    "A broadcast/build side whose estimated size exceeds "
    "spark.rapids.tpu.lint.maxDriverCollectBytes is collected whole "
    "(driver/device-resident single batch).  Spark chose a non-broadcast "
    "plan for such inputs precisely because they OOM the collector.")

L005 = register_rule(
    "TPU-L005", WARN, "JIT residency cache churn",
    "The plan's distinct (operator, schema) signatures exceed the "
    "compiled-program budget, or a scan pins an off-bucket batch "
    "capacity: each novel shape compiles a fresh XLA program family, "
    "evicting the residency cache (the round-5 multichip dryrun "
    "timeout).  Budget: spark.rapids.tpu.lint.maxCompiledPrograms; "
    "buckets: spark.rapids.tpu.batchCapacityBuckets.")

L006 = register_rule(
    "TPU-L006", ERROR, "partitioning contract consumed above rewrite",
    "An operator that assumes co-located/routed input (colocated hash "
    "join, FINAL-mode aggregate) sits above a subtree with no exchange "
    "to establish that contract — a rewrite stripped or reordered it, so "
    "the operator would silently merge unrouted rows (the bridge "
    "full-outer/per-partition class of wrong results).")

L007 = register_rule(
    "TPU-L007", WARN, "ICI exchange staging through host",
    "spark.rapids.shuffle.transport=ici is on but this exchange's column "
    "types cannot ride the all_to_all kernel, so rows silently stage "
    "through host Arrow — the accelerated transport is bypassed exactly "
    "where the plan moves the most data.")

L008 = register_rule(
    "TPU-L008", WARN, "opaque Python-UDF boundary in a device pipeline",
    "An out-of-process Python exchange operator (Arrow worker) consumes "
    "device-resident batches: every batch serializes to Arrow, crosses "
    "to the worker pool, and re-uploads.  Consider the UDF compiler "
    "(spark.rapids.sql.udfCompiler.enabled) or moving the UDF before "
    "upload.")

# rules whose host-fallback repair is sound (placement flip runs the
# identical xp-parameterized kernels on the host engine).  TPU-L011
# (contract broken by a rewrite) repairs exactly like L006: the flip
# clears the co-location assumption and the host path re-merges whole.
# TPU-L014 (peak over the HBM budget) first tries the cheaper repair —
# forcing the operator's out-of-core path (lifetime.try_outofcore_repair)
# — and host-flips only when no such path exists; the flip is sound
# because host RAM backs the working set instead of HBM.
# TPU-L009 is NOT here — a stale bind is wrong on either engine.
# TPU-L013/L015 are NOT here — a broken handle protocol (use-after-close
# / leak) is broken on either engine; only re-deriving the consumer
# count fixes it.
DOWNGRADE_CODES = {"TPU-L001", "TPU-L003", "TPU-L006", "TPU-L011",
                   "TPU-L014"}


# ---------------------------------------------------------------------------
# walk helpers
# ---------------------------------------------------------------------------

class LintContext:
    """What every rule check sees: the session conf plus (when the
    abstract interpreter ran) the per-node inferred states and liveness,
    so rules can be flow-sensitive with a syntactic fallback."""

    def __init__(self, conf: cfg.RapidsConf, interp=None):
        self.conf = conf
        self.interp = interp  # analysis.interp.InterpResult or None

    def get(self, entry):
        return self.conf.get(entry)

    def residency(self, node: eb.Exec) -> str:
        from .absdomain import DEVICE, HOST
        if self.interp is not None:
            return self.interp.residency(node)
        return DEVICE if node.placement == eb.TPU else HOST

    def live_names(self, node: eb.Exec):
        if self.interp is None:
            return None
        return self.interp.live_names(node)


def _walk(node: eb.Exec, parent: Optional[eb.Exec] = None, path: str = ""
          ) -> Iterator[Tuple[eb.Exec, Optional[eb.Exec], str]]:
    here = f"{path} > {node.name}" if path else node.name
    yield node, parent, here
    for c in node.children:
        yield from _walk(c, node, here)


def _aggregate_buffer_types(node) -> List[t.DataType]:
    out: List[t.DataType] = []
    for ae in getattr(node, "aggregates", []) or []:
        fn = getattr(ae, "func", None)
        if fn is None:
            continue
        try:
            out.extend(fn.buffer_types())
        except Exception:
            pass  # unbound aggregate: nothing provable about its buffers
    return out


def _is_exchange(node: eb.Exec) -> bool:
    from ..parallel.ici_exec import IciExchangeExec
    from ..shuffle.exchange import ShuffleExchangeExec
    return isinstance(node, (ShuffleExchangeExec, IciExchangeExec))


# ---------------------------------------------------------------------------
# per-node rule checks
# ---------------------------------------------------------------------------

def _check_ici_admit_mismatch(ctx, node, parent, path):
    if ctx.get(cfg.SHUFFLE_TRANSPORT) != "ici":
        return
    if not hasattr(node, "aggregates") or getattr(node, "grouping", None):
        return
    from ..parallel.alltoall import allgather_supported, exchange_supported
    bufs = _aggregate_buffer_types(node)
    if not bufs:
        return
    if exchange_supported(bufs) is None:
        reason = allgather_supported(bufs)
        if reason:
            bad = ", ".join(dt.name for dt in
                            ALLGATHER_BATCH.unsupported(bufs))
            yield L001.diag(
                f"ungrouped aggregate buffers [{bad}] pass the exchange "
                f"admission gate but {ALLGATHER_BATCH.name} raises on "
                f"them ({reason}); the ICI replicate path would crash "
                f"mid-query — route this aggregate to the host path",
                loc=path, node=node)


def _check_ping_pong(ctx, node, parent, path):
    # flow-sensitive: decided on the INFERRED residency (which knows
    # forwarding operators and transitions), not the raw placement flag
    from .absdomain import DEVICE, HOST
    if ctx.residency(node) != HOST or parent is None:
        return
    if getattr(node, "deliberate_cpu", False):
        return  # python exchange: TPU-L008's finding, not a planning slip
    if ctx.residency(parent) == DEVICE and \
            any(ctx.residency(c) == DEVICE for c in node.children):
        yield L002.diag(
            f"{node.name} runs on host between device-resident "
            f"{parent.name} and a device-resident child: two "
            f"interconnect crossings per batch", loc=path, node=node)


def _check_host_expr_on_device(ctx, node, parent, path):
    if node.placement != eb.TPU:
        return
    exprs = _node_expressions(node)
    if not exprs:
        return
    from ..plan.overrides import ExprMeta
    child = node.children[0] if node.children else None
    names = child.output_names if child is not None else []
    dtypes = child.output_types if child is not None else []
    for e in exprs:
        try:
            meta = ExprMeta(e, ctx.conf, names, dtypes)
            meta.tag()
        except Exception:
            continue  # unbindable here != hazard; tagging owns that call
        if not meta.can_replace_tree:
            reasons = "; ".join(meta.all_reasons()[:3])
            yield L003.diag(
                f"{type(e).__name__} on device-placed {node.name}: "
                f"{reasons}", loc=path, node=node)


def _node_expressions(node: eb.Exec):
    from ..exec.basic import FilterExec, ProjectExec
    if isinstance(node, ProjectExec):
        return list(node.exprs)
    if isinstance(node, FilterExec):
        return [node.condition]
    return []


def _check_driver_collect(ctx, node, parent, path):
    from ..exec.broadcast import BroadcastExchangeExec
    from ..exec.join import HashJoinExec
    cap = ctx.get(cfg.LINT_MAX_DRIVER_COLLECT)
    build = None
    if isinstance(node, BroadcastExchangeExec):
        build = node.children[0]
    elif isinstance(node, HashJoinExec) and \
            not getattr(node, "colocated", False):
        # plain hash join concatenates its whole build side into one
        # batch (the bridge's executeCollect analog)
        build = node.children[1]
        if isinstance(build, BroadcastExchangeExec):
            build = None  # already reported at the exchange itself
    if build is None:
        return
    est = build.estimated_size_bytes()
    if est is not None and est > cap:
        yield L004.diag(
            f"{node.name} collects a ~{max(est >> 10, 1)} KiB build "
            f"side whole (threshold {cap >> 10} KiB); gate the "
            f"translation on the size estimate or broadcast-partition "
            f"it", loc=path, node=node)


def _check_ici_host_staging(ctx, node, parent, path):
    if ctx.get(cfg.SHUFFLE_TRANSPORT) != "ici":
        return
    from ..shuffle.exchange import ShuffleExchangeExec
    if not isinstance(node, ShuffleExchangeExec):
        return
    from ..parallel.alltoall import exchange_supported
    reason = exchange_supported(node.output_types)
    if reason:
        # flow-sensitive refinement: if only columns nothing above reads
        # block the transport, the real fix is dropping them (TPU-L010)
        hint = ""
        live = ctx.live_names(node)
        if live is not None:
            live_types = [dt for n, dt in zip(node.output_names,
                                              node.output_types)
                          if n in live]
            if exchange_supported(live_types) is None:
                hint = (" — only columns nothing above the exchange "
                        "reads block the transport; dropping them "
                        "(see TPU-L010) restores ICI")
        yield L007.diag(
            f"exchange falls off the ICI transport: {reason}{hint}",
            loc=path, node=node)


def _check_udf_boundary(ctx, node, parent, path):
    from ..exec.python_udf import ArrowEvalPythonExec
    opaque = getattr(node, "deliberate_cpu", False) or \
        isinstance(node, ArrowEvalPythonExec)
    if not opaque:
        return
    if any(c.placement == eb.TPU for c in node.children):
        yield L008.diag(
            f"{node.name} consumes device-resident batches through the "
            f"Arrow worker boundary (serialize + re-upload per batch)",
            loc=path, node=node)


def _check_partition_contract(ctx, node, parent, path):
    # flow-sensitive mode subsumes this: interp evaluates the operator's
    # declared input_contracts() against the INFERRED distribution (so a
    # filter/project between exchange and consumer no longer hides the
    # contract, and a wrong-keyed exchange no longer satisfies it)
    if ctx.interp is not None:
        return
    from ..exec.aggregate import TpuHashAggregateExec
    from ..exec.join import HashJoinExec
    from ..expr.aggregates import FINAL
    if isinstance(node, HashJoinExec) and \
            getattr(node, "colocated", False):
        if not all(_is_exchange(c) for c in node.children):
            yield L006.diag(
                "colocated hash join without an establishing exchange "
                "under both sides: matching keys are not co-located, "
                "per-partition results would be wrong", loc=path,
                node=node)
    if isinstance(node, TpuHashAggregateExec) and node.mode == FINAL \
            and node.grouping:
        child = node.children[0]
        if not (_is_exchange(child) or
                isinstance(child, TpuHashAggregateExec)):
            yield L006.diag(
                "FINAL-mode aggregate above a non-exchange child: "
                "partial buffers for one group may live in several "
                "partitions and would never merge", loc=path, node=node)


_NODE_CHECKS = [
    _check_ici_admit_mismatch,
    _check_ping_pong,
    _check_host_expr_on_device,
    _check_driver_collect,
    _check_ici_host_staging,
    _check_udf_boundary,
    _check_partition_contract,
]


# ---------------------------------------------------------------------------
# plan-level checks
# ---------------------------------------------------------------------------

def _check_compile_churn(conf, root) -> Iterator[Diagnostic]:
    budget = conf.get(cfg.LINT_MAX_PROGRAMS)
    shapes = set()
    buckets = set(conf.capacity_buckets)
    from ..exec.basic import LocalScanExec
    for node, _parent, path in _walk(root):
        if node.placement == eb.TPU:
            try:
                shapes.add((type(node).__name__, eb.schema_sig(node)))
            except Exception:
                pass
        if isinstance(node, LocalScanExec) and node.batch_rows and \
                node.batch_rows not in buckets:
            yield L005.diag(
                f"scan pins off-bucket batch capacity "
                f"{node.batch_rows} (buckets: "
                f"{sorted(buckets)}): every such capacity compiles a "
                f"fresh program family per operator above it",
                loc=path, node=node)
    if len(shapes) > budget:
        yield L005.diag(
            f"plan spans ~{len(shapes)} distinct compiled-program "
            f"shapes (budget {budget}); the JIT residency cache will "
            f"churn — coalesce schemas or raise "
            f"spark.rapids.tpu.lint.maxCompiledPrograms", loc=root.name,
            node=None)


# ---------------------------------------------------------------------------
# front end
# ---------------------------------------------------------------------------

def lint_plan(root: eb.Exec, conf: cfg.RapidsConf,
              infer: Optional[bool] = None) -> List[Diagnostic]:
    """Analyze a converted physical plan; returns sorted diagnostics
    (most severe first).  Pure — never mutates (or executes) the plan.

    `infer` controls the flow-sensitive mode: the abstract interpreter
    (analysis/interp.py) runs first, its per-node states upgrade
    L002/L006/L007 from syntactic to flow-sensitive and add the
    boundary rules L009-L012.  Default comes from
    spark.rapids.tpu.lint.infer (on); a failed interpretation degrades
    to the syntactic rules rather than killing planning."""
    if infer is None:
        infer = conf.get(cfg.LINT_INFER)
    diags: List[Diagnostic] = []
    interp_result = None
    if infer:
        try:
            from .interp import infer_plan
            interp_result = infer_plan(root, conf)
            diags.extend(interp_result.diags)
        except Exception as ex:  # degrade to syntactic, never kill planning
            interp_result = None
            diags.append(Diagnostic(
                "TPU-L000", INFO,
                f"abstract interpreter failed ({ex}); syntactic rules "
                f"only", loc=root.name))
        if interp_result is not None:
            # tmsan lifetime/peak pass (TPU-L013..L015) rides the same
            # inferred states; a failure degrades like the interpreter
            try:
                from .lifetime import analyze_memory
                diags.extend(
                    analyze_memory(root, conf, interp_result).diags)
            except Exception as ex:
                diags.append(Diagnostic(
                    "TPU-L000", INFO,
                    f"lifetime pass failed ({ex}); memory rules "
                    f"skipped", loc=root.name))
    ctx = LintContext(conf, interp_result)
    for node, parent, path in _walk(root):
        for check in _NODE_CHECKS:
            try:
                diags.extend(check(ctx, node, parent, path) or ())
            except Exception as ex:  # a broken rule must not kill planning
                diags.append(Diagnostic(
                    "TPU-L000", INFO,
                    f"lint rule {check.__name__} failed: {ex}", loc=path))
    diags.extend(_check_compile_churn(conf, root))
    if conf.get(cfg.DSAN_ENABLED):
        # tpudsan replay-class composition (TPU-L016) rides the same
        # pre-flight; a failed pass degrades like the interpreter
        try:
            from .determinism import classify_plan
            diags.extend(classify_plan(root, conf).diags)
        except Exception as ex:
            diags.append(Diagnostic(
                "TPU-L000", INFO,
                f"determinism pass failed ({ex}); replay rules "
                f"skipped", loc=root.name))
    if conf.get(cfg.XSAN_ENABLED) and interp_result is not None:
        # tpuxsan program-efficiency rules (TPU-L018/L020) ride the
        # same interp states; a failed pass degrades like the others
        try:
            from .hloaudit import audit_plan
            diags.extend(audit_plan(root, conf, interp_result))
        except Exception as ex:
            diags.append(Diagnostic(
                "TPU-L000", INFO,
                f"tpuxsan pass failed ({ex}); efficiency rules "
                f"skipped", loc=root.name))
    disabled = conf.raw("spark.rapids.tpu.lint.disable", "") or ""
    return sort_diagnostics(filter_suppressed(diags, disabled.split(",")))


def downgrade_hazards(root: eb.Exec, diags: List[Diagnostic],
                      conf: Optional[cfg.RapidsConf] = None) -> eb.Exec:
    """Apply the sound repairs: flagged subtrees (DOWNGRADE_CODES with
    error severity) fall back to the host engine — placement flips to
    CPU (the xp-parameterized kernels run identically on numpy), fused
    ICI stages restore their host-path originals, and broken co-location
    assumptions are cleared.  insert_transitions then brackets the
    boundary as usual.

    TPU-L014 (peak over the HBM budget) gets the cheaper repair first:
    operators with a spill-managed fallback are forced out-of-core
    (oc_budget) and stay on device; only nodes without such a path
    host-flip."""
    repaired: set = set()
    if conf is not None:
        from .lifetime import try_outofcore_repair
        for d in diags:
            if d.code == "TPU-L014" and d.node is not None:
                try:
                    if try_outofcore_repair(root, d.node, conf):
                        repaired.add(id(d.node))
                except Exception:
                    pass  # fall through to the host flip
        # TPU-L016 has its own in-place repair (force the aggregate's
        # canonical keyed merge under the flagged boundary); a host
        # flip would NOT help — order dependence is engine-independent
        # — so L016 never joins the flip set below
        from .determinism import try_stabilize_repair
        for d in diags:
            if d.code == "TPU-L016" and d.node is not None:
                try:
                    if try_stabilize_repair(root, d.node, conf):
                        repaired.add(id(d.node))
                except Exception:
                    pass  # unrepairable: diagnostic stands
        # TPU-L018's repair re-buckets the nearest filter speculatively
        # (hloaudit.try_rebucket_repair); a host flip would trade
        # padding for losing the device entirely, so like L016 it never
        # joins the flip set below
        from .hloaudit import try_rebucket_repair
        for d in diags:
            if d.code == "TPU-L018" and d.node is not None:
                try:
                    if try_rebucket_repair(root, d.node, conf):
                        repaired.add(id(d.node))
                except Exception:
                    pass  # unrepairable: diagnostic stands
    flagged = {id(d.node) for d in diags
               if d.node is not None and d.is_error and
               d.code in DOWNGRADE_CODES and id(d.node) not in repaired}
    if not flagged:
        return root

    from ..parallel import ici_exec as ici

    def restore_host(node: eb.Exec) -> eb.Exec:
        if isinstance(node, ici.IciAggregateExec):
            return node.final_agg
        if isinstance(node, ici.IciSortExec):
            return node.sort_exec
        if isinstance(node, ici.IciJoinExec):
            return node.join_exec
        if isinstance(node, ici.IciExchangeExec):
            return node.exchange
        return node

    def to_host(node: eb.Exec) -> eb.Exec:
        node = restore_host(node)
        node.placement = eb.CPU
        if hasattr(node, "colocated"):
            node.colocated = False
        for c in node.children:
            to_host(c)
        return node

    def fix(node: eb.Exec) -> eb.Exec:
        if id(node) in flagged:
            return to_host(node)
        new_children = [fix(c) for c in node.children]
        if any(a is not b for a, b in zip(new_children, node.children)):
            node = node.with_new_children(new_children)
        return node

    return fix(root)


# ---------------------------------------------------------------------------
# event-log front end (qualification surfacing)
# ---------------------------------------------------------------------------

# marker -> (rule, message); matched against lowercased node text of a
# parsed Spark plan (tools/eventlog.py PlanNode) — the offline analog of
# the exec-tree rules above, so qualification reports carry the same
# TPU-Lxxx vocabulary
_SPARK_PLAN_MARKERS = [
    (("rlike", "regexp_extract", "regexp_replace"), L003,
     "regex expression evaluates on the host engine"),
    (("udf",), L008, "opaque UDF forces an Arrow worker boundary"),
    (("cartesianproduct", "broadcastnestedloopjoin"), L004,
     "whole-side collect/replication join"),
]


def lint_spark_plan(plan) -> List[Diagnostic]:
    """Heuristic text-level lint of a parsed event-log plan (PlanNode).
    Severities are capped at WARN: without types/configs nothing here is
    provably fatal — the codes exist so qualification output speaks the
    same rule vocabulary as the live plan lint."""
    diags: List[Diagnostic] = []
    seen = set()
    for node in plan.walk():
        text = (node.node_name + " " + node.simple_string).lower()
        for markers, rule, msg in _SPARK_PLAN_MARKERS:
            if any(m in text for m in markers):
                key = (rule.code, node.node_name)
                if key in seen:
                    continue
                seen.add(key)
                sev = WARN if rule.severity == ERROR else rule.severity
                diags.append(rule.diag(f"{msg} ({node.node_name})",
                                       loc=node.node_name,
                                       severity=sev))
        if "hashaggregate(keys=[]" in text.replace(" ", "") and \
                ("collect_list" in text or "collect_set" in text):
            key = ("TPU-L001", node.node_name)
            if key not in seen:
                seen.add(key)
                diags.append(L001.diag(
                    "global collect_list/collect_set: array buffers "
                    "cannot ride the ICI replicate path "
                    f"({node.node_name})", loc=node.node_name,
                    severity=WARN))
    return sort_diagnostics(diags)
