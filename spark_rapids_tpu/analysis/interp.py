"""Flow-sensitive abstract interpreter over the Exec IR.

Walks a converted physical plan bottom-up propagating one
``absdomain.AbstractState`` per subtree (schema, residency,
partitioning/ordering contract, size bounds — see ``absdomain.py``) and
verifies every producer/consumer interface along the way.  Mismatches
become typed diagnostics in the existing TPU-Lxxx framework:

  TPU-L009  schema mismatch at an exec boundary: an operator's *bound*
            expressions (ordinals + dtypes frozen at construction)
            disagree with the schema its child actually produces —
            the stale-bind class that ``with_new_children`` rewrites
            and AQE surgery can introduce.
  TPU-L010  dead columns shipped across an exchange: a column the
            exchange moves that no operator above ever reads, with the
            estimated wasted ICI/shuffle bytes.
  TPU-L011  partitioning contract violated after a rewrite: a consumer
            declaring a co-location requirement sits above a subtree
            whose exchanges establish an INCOMPATIBLE routing (keys /
            partition count changed between establishment and use).
            The never-established flavor keeps its original TPU-L006
            code — now decided on the inferred distribution rather
            than "is my direct child an exchange".
  TPU-L012  residency ping-pong: a root-to-leaf path whose batches
            cross the host<->device boundary two or more times, with
            the estimated bytes moved per pass.

Interface requirements are DECLARED by the operators themselves
(``Exec.input_contracts()`` — colocated joins return a
``CoClusteredContract``, FINAL-mode grouped aggregates a
``ClusteredContract``) and enforced here; the differential oracle
(``analysis/oracle.py``) checks the interpreter's predictions against
real numpy-backend execution so the analyzer can never drift from the
engine (the ``capabilities.verify_gates()`` discipline applied to the
analyzer itself).

The interpreter is total: a node it cannot model precisely degrades to
its declared schema with unknown distribution — conservative facts can
suppress a finding but never invent one.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from .. import config as cfg
from .. import types as t
from ..exec import base as eb
from .absdomain import (DEVICE, HOST, REPLICATED, SINGLE, UNKNOWN,
                        AbstractState, Dist, HashDist, UnknownDist,
                        schema_width)
from .diagnostics import ERROR, WARN, Diagnostic, register_rule

L009 = register_rule(
    "TPU-L009", ERROR, "schema mismatch at an exec boundary",
    "An operator's bound expressions reference input ordinals or dtypes "
    "that disagree with the schema its child actually produces — a "
    "with_new_children/AQE rewrite swapped the subtree after binding.  "
    "Executing would read the wrong column or mis-type a kernel; the "
    "operator must be re-bound against its new input.")

L010 = register_rule(
    "TPU-L010", WARN, "dead columns shipped across an exchange",
    "An exchange moves columns no operator above it ever reads.  Every "
    "byte of a dead column still rides the wire (ICI all_to_all lanes "
    "or host Arrow staging); project them away below the exchange.  "
    "The message carries the estimated wasted bytes from the same row "
    "model the cost-based optimizer uses.")

L011 = register_rule(
    "TPU-L011", ERROR, "partitioning contract violated after rewrite",
    "An operator declaring a co-location requirement "
    "(Exec.input_contracts) consumes a subtree whose exchanges "
    "establish an INCOMPATIBLE routing — keys or partition counts "
    "changed between the exchange that established the contract and "
    "the operator reusing it (the AQE/rewrite-reuse class).  Rows for "
    "one key would be merged per-partition in different partitions: "
    "silently wrong results.")

L012 = register_rule(
    "TPU-L012", WARN, "residency ping-pong along a plan path",
    "A root-to-leaf path crosses the host<->device boundary two or "
    "more times: each crossing pays the interconnect's fixed latency "
    "per batch plus the batch's bytes.  The message totals the "
    "estimated bytes moved along the path; hoist the host island out "
    "of the device pipeline or fall the whole path back.")


# ---------------------------------------------------------------------------
# result container
# ---------------------------------------------------------------------------

class InterpResult:
    """States keyed by id(node), liveness (columns read above a node),
    and the boundary diagnostics discovered during the walk."""

    def __init__(self):
        self.states: Dict[int, AbstractState] = {}
        self.live: Dict[int, Set[str]] = {}
        self.diags: List[Diagnostic] = []

    def state(self, node: eb.Exec) -> Optional[AbstractState]:
        return self.states.get(id(node))

    def live_names(self, node: eb.Exec) -> Optional[Set[str]]:
        return self.live.get(id(node))

    def residency(self, node: eb.Exec) -> str:
        st = self.states.get(id(node))
        if st is not None:
            return st.residency
        return DEVICE if node.placement == eb.TPU else HOST


# ---------------------------------------------------------------------------
# transfer helpers
# ---------------------------------------------------------------------------

def _placement_residency(node: eb.Exec) -> str:
    return DEVICE if node.placement == eb.TPU else HOST


def _rows_of(node: eb.Exec, child_states: Sequence[AbstractState]) -> float:
    from ..plan.cost import DEFAULT_ROW_COUNT, estimate_rows
    child_rows = [s.rows if s.rows is not None else float(DEFAULT_ROW_COUNT)
                  for s in child_states]
    try:
        return estimate_rows(node, child_rows)
    except Exception:
        return child_rows[0] if child_rows else float(DEFAULT_ROW_COUNT)


def _passthrough_map(exprs, child_names: Sequence[str]) -> Dict[str, str]:
    """child column name -> output name for expressions that forward a
    column unchanged (AttributeReference or Alias of one); the map that
    decides which distribution/ordering facts survive a projection."""
    from ..expr.core import Alias, AttributeReference, BoundReference, \
        output_name
    out: Dict[str, str] = {}
    for e in exprs:
        target = e.children[0] if isinstance(e, Alias) and e.children else e
        src = None
        if isinstance(target, AttributeReference):
            src = target.name
        elif isinstance(target, BoundReference):
            if 0 <= target.ordinal < len(child_names):
                src = child_names[target.ordinal]
        if src is not None and src not in out:
            out[src] = output_name(e)
    return out


def _remap_dist(dist: Dist, mapping: Dict[str, str]) -> Dist:
    if isinstance(dist, HashDist):
        if all(k in mapping for k in dist.keys):
            return HashDist([mapping[k] for k in dist.keys],
                            dist.num_partitions)
        return UnknownDist()
    return dist


def _remap_ordering(ordering, mapping: Dict[str, str]):
    out = []
    for name, asc in ordering:
        if name not in mapping:
            break  # ordering is a prefix contract
        out.append((mapping[name], asc))
    return tuple(out)


def _child_passthrough(node: eb.Exec, st: AbstractState,
                       **overrides) -> AbstractState:
    out = st.replace(residency=_placement_residency(node))
    for k, v in overrides.items():
        setattr(out, k, v)
    return out


def _fallback_state(node: eb.Exec,
                    child_states: Sequence[AbstractState]) -> AbstractState:
    """Declared schema, no optimistic facts — the degradation for execs
    the interpreter does not model."""
    try:
        names = list(node.output_names)
        dtypes = list(node.output_types)
    except Exception:
        names, dtypes = [], []
    return AbstractState(
        names, dtypes,
        residency=_placement_residency(node),
        dist=UNKNOWN,
        rows=_rows_of(node, child_states),
        num_partitions=(child_states[0].num_partitions
                        if child_states else None),
        saw_exchange=any(s.saw_exchange for s in child_states))


# ---------------------------------------------------------------------------
# per-exec transfer functions
# ---------------------------------------------------------------------------

def _dist_of_partitioning(part, child_names: Sequence[str]) -> Dist:
    from ..shuffle.partitioning import (HashPartitioning,
                                        SinglePartitioning)
    from ..expr.core import AttributeReference
    if isinstance(part, SinglePartitioning):
        return SINGLE
    if isinstance(part, HashPartitioning):
        keys = []
        for k in part.keys:
            if isinstance(k, AttributeReference) and k.name in child_names:
                keys.append(k.name)
            else:
                return UnknownDist()
        return HashDist(keys, part.num_partitions)
    return UnknownDist()


def _transfer(node: eb.Exec, child_states: List[AbstractState],
              conf: cfg.RapidsConf) -> AbstractState:
    from ..exec.basic import (CoalesceBatchesExec, FilterExec,
                              GlobalLimitExec, LocalLimitExec,
                              LocalScanExec, ProjectExec, RangeExec,
                              SampleExec, UnionExec)
    from ..exec.gatherpart import GatherPartitionsExec
    from ..exec.sort import SortExec
    from ..expr.core import AttributeReference, bind_expression, output_name

    saw = any(s.saw_exchange for s in child_states)
    rows = _rows_of(node, child_states)

    if isinstance(node, LocalScanExec):
        nullable = [f.nullable for f in node.table.schema]
        return AbstractState(
            node.output_names, node.output_types, nullable,
            residency=_placement_residency(node),
            dist=SINGLE if node.num_partitions == 1 else UNKNOWN,
            rows=float(node.table.num_rows),
            num_partitions=node.num_partitions)

    if isinstance(node, RangeExec):
        return AbstractState(
            node.output_names, node.output_types, [False],
            residency=_placement_residency(node),
            dist=SINGLE if node.num_partitions == 1 else UNKNOWN,
            rows=rows, num_partitions=node.num_partitions)

    if isinstance(node, ProjectExec):
        st = child_states[0]
        names = [output_name(e) for e in node.exprs]
        dtypes = []
        nullable = []
        for e in node.exprs:
            b = bind_expression(e, st.names, st.dtypes)
            dtypes.append(b.data_type())
            nullable.append(bool(getattr(b, "nullable", True)))
        mapping = _passthrough_map(node.exprs, st.names)
        return AbstractState(
            names, dtypes, nullable,
            residency=_placement_residency(node),
            dist=_remap_dist(st.dist, mapping),
            ordering=_remap_ordering(st.ordering, mapping),
            rows=rows, num_partitions=st.num_partitions,
            saw_exchange=saw)

    if isinstance(node, (FilterExec, SampleExec, LocalLimitExec,
                         GlobalLimitExec, CoalesceBatchesExec)):
        return _child_passthrough(node, child_states[0], rows=rows,
                                  saw_exchange=saw)

    if isinstance(node, SortExec):
        st = child_states[0]
        ordering = []
        for e, asc, _nf in node.orders:
            if isinstance(e, AttributeReference) and e.name in st.names:
                ordering.append((e.name, bool(asc)))
            else:
                break  # a computed sort key ends the nameable prefix
        return _child_passthrough(node, st, ordering=tuple(ordering),
                                  rows=rows, saw_exchange=saw)

    if isinstance(node, GatherPartitionsExec):
        st = child_states[0]
        keep_order = st.ordering if (st.num_partitions or 0) == 1 else ()
        return st.replace(dist=SINGLE, num_partitions=1,
                          ordering=keep_order, saw_exchange=saw)

    if isinstance(node, UnionExec):
        st = child_states[0]
        parts = None
        if all(s.num_partitions is not None for s in child_states):
            parts = sum(s.num_partitions for s in child_states)
        return AbstractState(
            st.names, st.dtypes,
            [any(s.nullable[i] if i < len(s.nullable) else True
                 for s in child_states)
             for i in range(len(st.names))],
            residency=st.residency, dist=UNKNOWN, rows=rows,
            num_partitions=parts, saw_exchange=saw)

    # -- transitions ---------------------------------------------------------
    if isinstance(node, eb.HostToDeviceExec):
        return child_states[0].replace(residency=DEVICE)
    if isinstance(node, eb.DeviceToHostExec):
        return child_states[0].replace(residency=HOST)

    # -- exchanges -----------------------------------------------------------
    from ..shuffle.exchange import ShuffleExchangeExec
    if isinstance(node, ShuffleExchangeExec):
        st = child_states[0]
        return st.replace(
            residency=_placement_residency(node),
            dist=_dist_of_partitioning(node.partitioning, st.names),
            ordering=(),
            num_partitions=node.partitioning.num_partitions,
            rows=rows, saw_exchange=True)

    from ..exec.broadcast import BroadcastExchangeExec
    if isinstance(node, BroadcastExchangeExec):
        st = child_states[0]
        return st.replace(residency=_placement_residency(node),
                          dist=REPLICATED, ordering=(), num_partitions=1,
                          rows=rows, saw_exchange=True)

    from ..shuffle.aqe import AQEShuffleReadExec, _SkewAwareRead
    if isinstance(node, AQEShuffleReadExec):
        st = child_states[0]
        if isinstance(node, _SkewAwareRead):
            # skew split scatters one reduce partition's blocks across
            # several output partitions: clustering is GONE
            dist: Dist = UNKNOWN
        elif node.replicate_for is not None:
            dist = REPLICATED
        elif isinstance(st.dist, HashDist):
            # partition coalescing preserves clustering, count unknown
            dist = HashDist(st.dist.keys, None)
        else:
            dist = st.dist
        return st.replace(dist=dist, num_partitions=None, ordering=(),
                          saw_exchange=True)

    # -- joins ---------------------------------------------------------------
    from ..exec.join import HashJoinExec, NestedLoopJoinExec
    if isinstance(node, HashJoinExec):
        l, r = child_states
        if node.how in ("left_semi", "left_anti"):
            names, dtypes = list(l.names), list(l.dtypes)
            nullable = list(l.nullable)
        else:
            names = list(l.names) + list(r.names)
            dtypes = list(l.dtypes) + list(r.dtypes)
            r_null = [True] * len(r.names) if node.how in ("left", "full") \
                else list(r.nullable)
            l_null = [True] * len(l.names) if node.how in ("right", "full") \
                else list(l.nullable)
            nullable = l_null + r_null
        dist = l.dist if node.how in ("inner", "left", "left_semi",
                                      "left_anti") else UNKNOWN
        if isinstance(dist, HashDist) and \
                not set(dist.keys) <= set(names):
            dist = UNKNOWN
        return AbstractState(
            names, dtypes, nullable,
            residency=_placement_residency(node), dist=dist, rows=rows,
            num_partitions=l.num_partitions, saw_exchange=saw)

    if isinstance(node, NestedLoopJoinExec):
        l, r = child_states
        return AbstractState(
            list(l.names) + list(r.names),
            list(l.dtypes) + list(r.dtypes),
            residency=_placement_residency(node), dist=UNKNOWN,
            rows=rows, num_partitions=l.num_partitions,
            saw_exchange=saw)

    # -- aggregates ----------------------------------------------------------
    from ..exec.aggregate import TpuHashAggregateExec
    from ..expr.aggregates import Count, FINAL, PARTIAL
    if isinstance(node, TpuHashAggregateExec):
        st = child_states[0]
        k = len(node.grouping)
        if node.mode == FINAL:
            gnames = list(st.names[:k])
            gtypes = list(st.dtypes[:k])
        else:
            gnames = [output_name(g) for g in node.grouping]
            gtypes = [bind_expression(g, st.names, st.dtypes).data_type()
                      for g in node.grouping]
        if node.mode == PARTIAL:
            names = gnames + node._buffer_names
            dtypes = gtypes + node._buffer_types
            nullable = [True] * len(names)
        else:
            names = gnames + [ae.name for ae in node.aggregates]
            dtypes = gtypes + [ae.data_type() for ae in node.aggregates]
            nullable = [True] * k + [
                not isinstance(ae.func, Count) for ae in node.aggregates]
        # grouped rows keep the child's clustering when the keys survive
        if node.mode == FINAL:
            mapping = {n: n for n in gnames}
        else:
            mapping = _passthrough_map(node.grouping, st.names)
        dist = _remap_dist(st.dist, mapping) if k else \
            (SINGLE if (st.num_partitions or 0) == 1 else st.dist)
        return AbstractState(
            names, dtypes, nullable,
            residency=_placement_residency(node), dist=dist, rows=rows,
            num_partitions=st.num_partitions, saw_exchange=saw)

    # -- ICI fused stages ----------------------------------------------------
    from ..parallel.ici_exec import IciExchangeExec
    if isinstance(node, IciExchangeExec):
        st = child_states[0]
        return st.replace(
            residency=DEVICE,
            dist=_dist_of_partitioning(node.exchange.partitioning,
                                       st.names),
            ordering=(),
            num_partitions=node.exchange.partitioning.num_partitions,
            saw_exchange=True)

    # anything else (python exchanges, window, expand, generate, cached
    # scans, fused ICI stages, ...): declared schema, no optimistic facts
    return _fallback_state(node, child_states)


# ---------------------------------------------------------------------------
# boundary checks
# ---------------------------------------------------------------------------

def _bound_expr_sites(node: eb.Exec) -> List[Tuple[object, int]]:
    """(bound expression, child index) pairs whose BoundReferences were
    frozen against the child's schema at construction time."""
    from ..exec.basic import FilterExec, ProjectExec
    from ..exec.sort import SortExec
    from ..exec.join import HashJoinExec
    from ..exec.aggregate import TpuHashAggregateExec
    from ..expr.aggregates import COMPLETE, PARTIAL
    out: List[Tuple[object, int]] = []
    if isinstance(node, ProjectExec):
        out += [(b, 0) for b in node._bound]
    elif isinstance(node, FilterExec):
        out.append((node._bound, 0))
    elif isinstance(node, SortExec):
        out += [(e, 0) for e, _asc, _nf in node._bound]
    elif isinstance(node, HashJoinExec):
        out += [(k, 0) for k in node.left_keys]
        out += [(k, 1) for k in node.right_keys]
    elif isinstance(node, TpuHashAggregateExec):
        if node.mode in (PARTIAL, COMPLETE):
            out += [(g, 0) for g in node._bound_grouping]
            out += [(u, 0) for u in node._update_inputs]
    return out


def _check_bound_refs(node: eb.Exec, child_states: List[AbstractState],
                      path: str) -> List[Diagnostic]:
    from ..expr.core import BoundReference
    diags: List[Diagnostic] = []
    seen: Set[Tuple[int, int]] = set()
    for bexpr, ci in _bound_expr_sites(node):
        if ci >= len(child_states):
            continue
        st = child_states[ci]
        try:
            refs = bexpr.collect(
                lambda e: isinstance(e, BoundReference))
        except Exception:
            continue
        for br in refs:
            key = (ci, br.ordinal)
            if key in seen:
                continue
            if br.ordinal >= len(st.names) or br.ordinal < 0:
                seen.add(key)
                diags.append(L009.diag(
                    f"{node.name} is bound to input ordinal "
                    f"{br.ordinal} ({br.name}) but its child produces "
                    f"only {len(st.names)} column(s) — the subtree was "
                    f"swapped after binding; re-bind the operator",
                    loc=path, node=node))
            elif repr(br.dtype) != repr(st.dtypes[br.ordinal]):
                seen.add(key)
                diags.append(L009.diag(
                    f"{node.name} is bound to ordinal {br.ordinal} as "
                    f"{br.dtype.name} but the child now produces "
                    f"{st.dtypes[br.ordinal].name} "
                    f"({st.names[br.ordinal]}) — stale bind after a "
                    f"rewrite", loc=path, node=node))
    # union arms must agree column-for-column
    from ..exec.basic import UnionExec
    if isinstance(node, UnionExec) and len(child_states) > 1:
        first = child_states[0]
        for i, st in enumerate(child_states[1:], start=1):
            if len(st.dtypes) != len(first.dtypes) or any(
                    repr(a) != repr(b)
                    for a, b in zip(first.dtypes, st.dtypes)):
                diags.append(L009.diag(
                    f"union arm {i} produces "
                    f"[{', '.join(dt.name for dt in st.dtypes)}] but arm "
                    f"0 produces "
                    f"[{', '.join(dt.name for dt in first.dtypes)}]",
                    loc=path, node=node))
    return diags


def _check_contracts(node: eb.Exec, child_states: List[AbstractState],
                     path: str) -> List[Diagnostic]:
    try:
        contract = node.input_contracts()
    except Exception:
        return []
    if contract is None:
        return []
    try:
        violations = contract.check(child_states)
    except Exception:
        return []
    diags = []
    for v in violations:
        established = any(s.saw_exchange for s in child_states)
        rule = L011 if established else None
        if rule is None:
            # never established: the original TPU-L006 class, now decided
            # on the inferred distribution instead of node shape
            from .plan_lint import L006
            rule = L006
        diags.append(rule.diag(v, loc=path, node=node))
    return diags


# ---------------------------------------------------------------------------
# plan-level passes: liveness (L010) and residency paths (L012)
# ---------------------------------------------------------------------------

def _bound_read_names(bexprs, st: AbstractState) -> Set[str]:
    from ..expr.core import AttributeReference, BoundReference
    out: Set[str] = set()
    for b in bexprs:
        try:
            refs = b.collect(lambda e: isinstance(
                e, (BoundReference, AttributeReference)))
        except Exception:
            return set(st.names)
        for r in refs:
            if isinstance(r, BoundReference):
                if 0 <= r.ordinal < len(st.names):
                    out.add(st.names[r.ordinal])
            elif r.name in st.names:
                out.add(r.name)
    return out


def _child_reads(node: eb.Exec, live_out: Set[str],
                 child_states: List[AbstractState]) -> List[Set[str]]:
    """Columns each child must produce for `node` to serve `live_out`.
    Conservative default: everything."""
    from ..exec.basic import (CoalesceBatchesExec, FilterExec,
                              GlobalLimitExec, LocalLimitExec, ProjectExec,
                              SampleExec, UnionExec)
    from ..exec.gatherpart import GatherPartitionsExec
    from ..exec.sort import SortExec
    from ..exec.join import HashJoinExec
    from ..exec.aggregate import TpuHashAggregateExec
    from ..expr.aggregates import COMPLETE, FINAL, PARTIAL
    from ..shuffle.exchange import ShuffleExchangeExec

    if not node.children:
        return []
    st0 = child_states[0]

    if isinstance(node, ProjectExec):
        from ..expr.core import output_name
        wanted = [b for e, b in zip(node.exprs, node._bound)
                  if output_name(e) in live_out]
        return [_bound_read_names(wanted, st0)]
    if isinstance(node, FilterExec):
        return [(live_out & set(st0.names)) |
                _bound_read_names([node._bound], st0)]
    if isinstance(node, SortExec):
        return [(live_out & set(st0.names)) |
                _bound_read_names([e for e, _a, _n in node._bound], st0)]
    if isinstance(node, (SampleExec, LocalLimitExec, GlobalLimitExec,
                         CoalesceBatchesExec, GatherPartitionsExec,
                         eb.HostToDeviceExec, eb.DeviceToHostExec)):
        return [live_out & set(st0.names)]
    if isinstance(node, UnionExec):
        return [live_out & set(s.names) for s in child_states]
    if isinstance(node, ShuffleExchangeExec):
        keys = set()
        bound = getattr(node.partitioning, "_bound", None)
        if bound is not None:
            keys = _bound_read_names([bound], st0)
        else:
            orders = getattr(node.partitioning, "_bound_orders", None)
            if orders:
                keys = _bound_read_names([e for e, _a, _n in orders], st0)
        return [(live_out & set(st0.names)) | keys]
    if isinstance(node, HashJoinExec):
        l, r = child_states
        lread = (live_out & set(l.names)) | _bound_read_names(
            node.left_keys, l)
        rread = _bound_read_names(node.right_keys, r)
        if node.how not in ("left_semi", "left_anti"):
            rread |= live_out & set(r.names)
        if node.condition is not None:
            lread, rread = set(l.names), set(r.names)
        return [lread, rread]
    if isinstance(node, TpuHashAggregateExec):
        if node.mode == FINAL:
            return [set(st0.names)]  # every buffer column merges
        reads = _bound_read_names(
            list(node._bound_grouping) + list(node._update_inputs), st0)
        return [reads]
    return [set(s.names) for s in child_states]


def _liveness_pass(root: eb.Exec, result: InterpResult) -> None:
    root_state = result.state(root)
    if root_state is None:
        return

    def down(node: eb.Exec, live_out: Set[str]):
        result.live[id(node)] = set(live_out)
        child_states = [result.state(c) or
                        AbstractState(c.output_names, c.output_types)
                        for c in node.children]
        try:
            reads = _child_reads(node, live_out, child_states)
        except Exception:
            reads = [set(s.names) for s in child_states]
        for c, r in zip(node.children, reads):
            down(c, r)

    down(root, set(root_state.names))


def _is_exchange_node(node: eb.Exec) -> bool:
    from ..shuffle.exchange import ShuffleExchangeExec
    from ..exec.broadcast import BroadcastExchangeExec
    from ..parallel.ici_exec import IciExchangeExec
    return isinstance(node, (ShuffleExchangeExec, BroadcastExchangeExec,
                             IciExchangeExec))


def _check_dead_columns(root: eb.Exec, result: InterpResult,
                        conf: cfg.RapidsConf) -> List[Diagnostic]:
    diags: List[Diagnostic] = []

    def walk(node: eb.Exec, path: str):
        here = f"{path} > {node.name}" if path else node.name
        st = result.state(node)
        live = result.live_names(node)
        if st is not None and live is not None and \
                _is_exchange_node(node):
            # partitioning keys are read by the router itself
            child_states = [result.state(c) for c in node.children]
            if all(s is not None for s in child_states):
                keys: Set[str] = set()
                reads = _child_reads(node, live, child_states)
                if reads:
                    keys = reads[0]
                dead = [(n, dt) for n, dt in zip(st.names, st.dtypes)
                        if n not in live and n not in keys]
                if dead:
                    rows = st.rows or 0.0
                    wasted = int(rows * schema_width([dt for _n, dt
                                                      in dead]))
                    wire = "ICI" if conf.get(cfg.SHUFFLE_TRANSPORT) == \
                        "ici" else "shuffle"
                    cols = ", ".join(n for n, _dt in dead)
                    diags.append(L010.diag(
                        f"{node.name} ships column(s) [{cols}] that "
                        f"nothing above the exchange reads "
                        f"(~{max(wasted >> 10, 1)} KiB wasted {wire} "
                        f"bytes); project them away below the exchange",
                        loc=here, node=node))
        for c in node.children:
            walk(c, here)

    walk(root, "")
    return diags


def _check_residency_paths(root: eb.Exec,
                           result: InterpResult) -> List[Diagnostic]:
    """Host islands strictly inside a device region along a root-to-leaf
    path: data already resident on device comes down and goes straight
    back up.  (A device region inside a host pipeline is the NORMAL
    accelerated shape — upload, compute, fetch — and is never flagged.)
    Each island costs two crossings; bytes total the states moved over
    both edges."""
    diags: List[Diagnostic] = []
    seen: Set[Tuple[str, int]] = set()

    def down(node: eb.Exec, path: str, runs: List[Tuple[str, float, str]]):
        here = f"{path} > {node.name}" if path else node.name
        res = result.residency(node)
        if not runs or runs[-1][0] != res:
            st = result.state(node)
            b = (st.bytes_estimate() or 0.0) if st is not None else 0.0
            runs = runs + [(res, b, here)]
        if not node.children:
            islands = [i for i in range(1, len(runs) - 1)
                       if runs[i][0] == HOST and
                       runs[i - 1][0] == DEVICE and
                       runs[i + 1][0] == DEVICE]
            if islands:
                crossings = 2 * len(islands)
                bytes_ = sum(runs[i][1] + runs[i + 1][1]
                             for i in islands)
                loc = runs[islands[0]][2]
                key = (loc, crossings)
                if key not in seen:
                    seen.add(key)
                    diags.append(L012.diag(
                        f"{len(islands)} host island(s) inside a device "
                        f"pipeline: the path crosses host<->device "
                        f"{crossings} extra times "
                        f"(~{max(int(bytes_) >> 10, 1)} KiB moved per "
                        f"pass); hoist the host work out of the device "
                        f"pipeline or fall the whole path back",
                        loc=loc, node=None))
        for c in node.children:
            down(c, here, runs)

    down(root, "", [])
    return diags


# ---------------------------------------------------------------------------
# front end
# ---------------------------------------------------------------------------

def infer_plan(root: eb.Exec, conf: cfg.RapidsConf,
               row_overrides: Optional[Dict[int, float]] = None
               ) -> InterpResult:
    """Run the abstract interpreter over a converted plan: fills in one
    AbstractState per node, the liveness map, and every boundary
    diagnostic (L009/L010/L011/L012 + flow-decided L006).  Pure — never
    mutates the plan, never executes it.

    ``row_overrides`` (id(node) -> rows) substitutes MEASURED row counts
    for the model's estimates at specific nodes — the exchange-boundary
    re-planner pins a materialized shuffle's real output here and the
    override propagates upward through every downstream transfer."""
    result = InterpResult()

    def up(node: eb.Exec, path: str) -> AbstractState:
        here = f"{path} > {node.name}" if path else node.name
        child_states = [up(c, here) for c in node.children]
        result.diags.extend(_check_bound_refs(node, child_states, here))
        result.diags.extend(_check_contracts(node, child_states, here))
        try:
            st = _transfer(node, child_states, conf)
        except Exception as ex:
            # deliberate degradation (the fallback state keeps the
            # interpreter total) — but record the swallowed error on
            # the flight recorder so a misbehaving transfer function
            # is diagnosable, not silent (tpufsan TPU-R011)
            from ..obs.tracer import trace_event
            trace_event("interp.transfer_fallback", node=node.name,
                        error=repr(ex))
            st = _fallback_state(node, child_states)
        if row_overrides and id(node) in row_overrides:
            st.rows = row_overrides[id(node)]
        result.states[id(node)] = st
        return st

    up(root, "")
    _liveness_pass(root, result)
    result.diags.extend(_check_dead_columns(root, result, conf))
    result.diags.extend(_check_residency_paths(root, result))
    return result


def format_states(root: eb.Exec, result: InterpResult) -> str:
    """Inferred-state tree for `tools lint --plan --infer` output."""
    lines: List[str] = []

    def walk(node: eb.Exec, level: int):
        st = result.state(node)
        desc = st.describe() if st is not None else "(no state)"
        live = result.live_names(node)
        dead = ""
        if live is not None and st is not None:
            unread = [n for n in st.names if n not in live]
            if unread and node.children:
                dead = f" unread=[{', '.join(unread)}]"
        lines.append(f"{'  ' * level}{node.name}: {desc}{dead}")
        for c in node.children:
            walk(c, level + 1)

    walk(root, 0)
    return "\n".join(lines) + "\n"
