"""Repo lint: AST + registry pass enforcing codebase invariants.

  TPU-R001  no implicit host sync (np.asarray / jax.device_get /
            .block_until_ready) inside exec/ and ops/ hot paths — the
            single-round-trip fetch path (columnar/fetch.py) is the only
            sanctioned device->host crossing
  TPU-R002  every SPARK_RAPIDS_* env var read is declared in
            config.DECLARED_ENV_KEYS (env knobs must be documented
            config surface, not scattered literals)
  TPU-R003  every public Expression subclass under expr/ is registered
            with a TypeSig in the overrides registry (an expression
            without a declared dtype coverage is un-taggable: the
            planner cannot prove where it runs)
  TPU-R004  every planning-time admission gate is no weaker than the
            kernel it guards (capabilities.verify_gates — the check that
            catches the round-5 alltoall admit/crash drift)
  TPU-R005  device allocations in exec/ and ops/ route through the
            catalog/arena APIs (SpillCatalog.register, batch_to_device,
            the shared staging arena) — an unrouted buffer is invisible
            to spill pressure, leak_report and the tmsan ledger
  TPU-R006  raw time.perf_counter*/TraceAnnotation in exec/, ops/,
            shuffle/, parallel/ must route through MetricTimer or the
            obs/ flight recorder (one timing path for metrics, traces
            and the self-emitted event log)

Pre-existing violations live in a checked-in baseline
(devtools/lint_baseline.txt, fingerprint per line); devtools/run_lint.py
exits nonzero only on NEW violations, so the invariant ratchets.
Deliberate single-site exceptions are annotated in place with
``# tpulint: allow[TPU-Rxxx] <reason>`` instead of baselined — the
annotation travels with the code it sanctions.
"""

from __future__ import annotations

import ast
import os
from typing import Iterable, List, Optional, Set

from .diagnostics import Diagnostic, ERROR, WARN, register_rule

R001 = register_rule(
    "TPU-R001", ERROR, "implicit host sync in hot path",
    "np.asarray / jax.device_get / .block_until_ready inside exec/ or "
    "ops/ forces a device round trip (tens of ms on a tunneled TPU) per "
    "call site; device->host crossings belong to columnar/fetch.py's "
    "batched two-round-trip path.")

R002 = register_rule(
    "TPU-R002", ERROR, "undeclared environment-variable config",
    "A SPARK_RAPIDS_* environment variable is read without being listed "
    "in config.DECLARED_ENV_KEYS; env knobs are config surface and must "
    "be declared and documented like every other key.")

R003 = register_rule(
    "TPU-R003", WARN, "expression without registered dtype coverage",
    "A public Expression subclass under expr/ has no entry in the "
    "overrides EXPR_RULES registry: the tagging engine cannot reason "
    "about its dtype coverage, so plans using it are un-analyzable.")

R004 = register_rule(
    "TPU-R004", ERROR, "planning gate weaker than kernel coverage",
    "A registered admission gate (capabilities.registered_gates) admits "
    "a dtype its runtime kernel raises on — plans pass planning and "
    "crash mid-query.  Tighten the gate or extend the kernel.")

R006 = register_rule(
    "TPU-R006", ERROR, "raw timing primitive outside MetricTimer/tracer",
    "time.perf_counter/perf_counter_ns or jax.profiler.TraceAnnotation "
    "used directly in exec/, ops/, shuffle/ or parallel/: operator "
    "timing must route through MetricTimer (which owns the sanctioned "
    "clock reads and the NVTX-analog annotation) or the obs/ flight "
    "recorder, so the engine has ONE timing path that metrics, traces "
    "and the self-emitted event log all agree on.")

R007 = register_rule(
    "TPU-R007", ERROR, "ad-hoc module-level metric tally",
    "A module-level mutable counter (integer tally, Counter(), "
    "defaultdict tally, or a dict/list/set whose name says it counts) "
    "in exec/, ops/, shuffle/, parallel/ or memory/: process-wide "
    "statistics must route through obs.metrics.MetricsRegistry so they "
    "are thread-safe, cardinality-bounded, and visible to the "
    "Prometheus/health exposition and the regression watchdog — an "
    "ad-hoc global is invisible to all three.  Sanctioned sinks are "
    "annotated `# tpulint: allow[TPU-R007]` in place.")

R005 = register_rule(
    "TPU-R005", ERROR, "device allocation outside the catalog/arena APIs",
    "Code in exec/ or ops/ constructs a SpillableBatch directly, calls "
    "jax.device_put, or builds a private HostArena: device buffers must "
    "enter through SpillCatalog.register/register_pinned (budgeted, "
    "spillable, visible to the tmsan shadow ledger), uploads through "
    "columnar.device.batch_to_device / HostToDeviceExec, and staging "
    "through the plugin's shared arena — an unrouted allocation is "
    "invisible to every memory-safety layer (spill pressure, "
    "leak_report, the TPU-L014 peak bound).")

# hot-path packages for TPU-R001/R005 (module-relative, forward slashes)
_HOT_PATHS = ("spark_rapids_tpu/exec/", "spark_rapids_tpu/ops/")
_SYNC_RECEIVERS = {"asarray": {"np", "numpy"}, "device_get": {"jax"}}
# one-timing-path packages for TPU-R006 (everywhere operator work runs)
_TIMING_PATHS = ("spark_rapids_tpu/exec/", "spark_rapids_tpu/ops/",
                 "spark_rapids_tpu/shuffle/", "spark_rapids_tpu/parallel/")
_TIMING_CALLS = {"perf_counter", "perf_counter_ns"}
# one-metrics-path packages for TPU-R007 (engine-statistics producers)
_TALLY_PATHS = _TIMING_PATHS + ("spark_rapids_tpu/memory/",)

# `# tpulint: allow[TPU-Rxxx] <reason>` on the flagged line or the line
# above sanctions one deliberate violation (the annotated-sink analog of
# the baseline, for sites that are the POINT of the rule's exception —
# e.g. maybe_sync IS the sanctioned device-timing sync)
import re as _re

_ALLOW_RE = _re.compile(r"tpulint:\s*allow\[([A-Z0-9-]+)\]")


def _allowed_lines(source: str) -> dict:
    """rule code -> set of line numbers (1-based) the annotation covers:
    its own line, any immediately following comment lines, and the first
    code line after them (so a multi-line reason can sit above the
    call)."""
    out: dict = {}
    lines = source.splitlines()
    for i, line in enumerate(lines, start=1):
        for code in _ALLOW_RE.findall(line):
            covered = out.setdefault(code, set())
            covered.add(i)
            j = i + 1
            while j <= len(lines) and \
                    lines[j - 1].lstrip().startswith("#"):
                covered.add(j)
                j += 1
            covered.add(j)
    return out


def _package_root() -> str:
    """Directory CONTAINING the spark_rapids_tpu package."""
    import spark_rapids_tpu
    return os.path.dirname(os.path.dirname(
        os.path.abspath(spark_rapids_tpu.__file__)))


def _py_files(root: str) -> Iterable[str]:
    pkg = os.path.join(root, "spark_rapids_tpu")
    for dirpath, _dirs, files in os.walk(pkg):
        for f in sorted(files):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


class _ScopedVisitor(ast.NodeVisitor):
    """Tracks the enclosing class/function qualname so fingerprints
    survive line-number churn."""

    def __init__(self):
        self._scope: List[str] = []

    @property
    def scope(self) -> str:
        return ".".join(self._scope) or "<module>"

    def visit_ClassDef(self, node):
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    def visit_FunctionDef(self, node):
        self._scope.append(node.name)
        self.generic_visit(node)
        self._scope.pop()

    visit_AsyncFunctionDef = visit_FunctionDef


class _HostSyncVisitor(_ScopedVisitor):
    def __init__(self, relpath: str):
        super().__init__()
        self.relpath = relpath
        self.diags: List[Diagnostic] = []

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute):
            call = None
            if f.attr == "block_until_ready":
                call = ".block_until_ready"
            elif f.attr in _SYNC_RECEIVERS and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id in _SYNC_RECEIVERS[f.attr]:
                call = f"{f.value.id}.{f.attr}"
            if call is not None:
                self.diags.append(R001.diag(
                    f"implicit host sync {call} in {self.scope}",
                    loc=f"{self.relpath}:{node.lineno}"))
        self.generic_visit(node)


class _DeviceAllocVisitor(_ScopedVisitor):
    """TPU-R005: direct device-buffer acquisition in exec//ops/ that
    bypasses the catalog/arena routing."""

    def __init__(self, relpath: str):
        super().__init__()
        self.relpath = relpath
        self.diags: List[Diagnostic] = []

    def visit_Call(self, node):
        f = node.func
        call = None
        if isinstance(f, ast.Name) and f.id in ("SpillableBatch",
                                                "HostArena"):
            call = f"{f.id}(...)"
        elif isinstance(f, ast.Attribute):
            if f.attr in ("SpillableBatch", "HostArena"):
                call = f"{f.attr}(...)"
            elif f.attr == "device_put" and \
                    isinstance(f.value, ast.Name) and \
                    f.value.id in ("jax", "jnp"):
                call = f"{f.value.id}.device_put"
        if call is not None:
            self.diags.append(R005.diag(
                f"unrouted device allocation {call} in {self.scope}; "
                f"route through SpillCatalog.register / "
                f"batch_to_device / the shared arena",
                loc=f"{self.relpath}:{node.lineno}"))
        self.generic_visit(node)


class _TimingVisitor(_ScopedVisitor):
    """TPU-R006: raw clock reads / profiler annotations in the operator
    packages that bypass the single timing path (MetricTimer + the
    obs/ tracer)."""

    def __init__(self, relpath: str):
        super().__init__()
        self.relpath = relpath
        self.diags: List[Diagnostic] = []

    def visit_Call(self, node):
        f = node.func
        call = None
        if isinstance(f, ast.Attribute) and f.attr in _TIMING_CALLS and \
                isinstance(f.value, ast.Name) and \
                f.value.id.lstrip("_") == "time":
            call = f"time.{f.attr}"
        elif isinstance(f, ast.Name) and f.id == "TraceAnnotation":
            call = "TraceAnnotation(...)"
        elif isinstance(f, ast.Attribute) and \
                f.attr == "TraceAnnotation":
            call = "TraceAnnotation(...)"
        if call is not None:
            self.diags.append(R006.diag(
                f"raw timing primitive {call} in {self.scope}; route "
                f"through MetricTimer or the obs/ tracer",
                loc=f"{self.relpath}:{node.lineno}"))
        self.generic_visit(node)


_TALLY_NAME = _re.compile(
    r"(^|_)(n|num|count(er)?s?|totals?|tall(y|ies)|hits?|miss(es)?|"
    r"calls?|stats?)(_|$|\d)", _re.I)


def _is_tally_name(name: str) -> bool:
    return bool(_TALLY_NAME.search(name))


def module_tally_diagnostics(source_or_tree, relpath: str):
    """TPU-R007 over ONE module's top level (factored out so tests can
    run it against synthetic sources).  Flags:

      * a module-level Counter()/defaultdict(int|float) binding — these
        containers exist to count, whatever the name says;
      * a module-level int/float literal, empty dict/list/set literal
        or dict()/list()/set() call bound to a counter-ish name
        (``_FOO_COUNT``, ``TOTALS``, ``_hits`` ...);
      * a module-level augmented assignment to a counter-ish name
        (``_N_CALLS += 1``).

    Lookup tables, caches and registries (names without a counting
    word) stay legal: the rule targets tallies, not constants.
    """
    tree = source_or_tree if isinstance(source_or_tree, ast.Module) \
        else ast.parse(source_or_tree, filename=relpath)
    diags: List[Diagnostic] = []

    def _is_counting_container(v) -> bool:
        if not isinstance(v, ast.Call):
            return False
        f = v.func
        name = f.id if isinstance(f, ast.Name) else \
            f.attr if isinstance(f, ast.Attribute) else ""
        if name == "Counter":
            return True
        if name == "defaultdict" and v.args and \
                isinstance(v.args[0], ast.Name) and \
                v.args[0].id in ("int", "float"):
            return True
        return False

    def _is_mutable_zero(v) -> bool:
        if isinstance(v, ast.Constant) and \
                isinstance(v.value, (int, float)) and \
                not isinstance(v.value, bool):
            return True
        if isinstance(v, (ast.Dict, ast.List, ast.Set)):
            return not (getattr(v, "keys", None) or
                        getattr(v, "elts", None))
        if isinstance(v, ast.Call) and isinstance(v.func, ast.Name) and \
                v.func.id in ("dict", "list", "set") and not v.args \
                and not v.keywords:
            return True
        return False

    for node in tree.body:
        targets = []
        value = None
        if isinstance(node, ast.Assign):
            targets = [t for t in node.targets
                       if isinstance(t, ast.Name)]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and \
                node.value is not None:
            targets = [node.target]
            value = node.value
        elif isinstance(node, ast.AugAssign) and \
                isinstance(node.target, ast.Name):
            if _is_tally_name(node.target.id):
                diags.append(R007.diag(
                    f"module-level tally mutation "
                    f"{node.target.id} {type(node.op).__name__}=; "
                    f"route through obs.metrics.MetricsRegistry",
                    loc=f"{relpath}:{node.lineno}"))
            continue
        if not targets or value is None:
            continue
        for t in targets:
            if _is_counting_container(value):
                diags.append(R007.diag(
                    f"module-level counting container {t.id}; route "
                    f"through obs.metrics.MetricsRegistry",
                    loc=f"{relpath}:{node.lineno}"))
            elif _is_tally_name(t.id) and _is_mutable_zero(value):
                diags.append(R007.diag(
                    f"module-level mutable tally {t.id}; route "
                    f"through obs.metrics.MetricsRegistry",
                    loc=f"{relpath}:{node.lineno}"))
    return diags


class _EnvReadVisitor(_ScopedVisitor):
    def __init__(self, relpath: str, declared: Set[str]):
        super().__init__()
        self.relpath = relpath
        self.declared = declared
        self.diags: List[Diagnostic] = []

    @staticmethod
    def _is_environ(node) -> bool:
        return isinstance(node, ast.Attribute) and \
            node.attr == "environ" and \
            isinstance(node.value, ast.Name) and \
            node.value.id.lstrip("_") == "os"

    def _check_key(self, key_node, lineno: int):
        if isinstance(key_node, ast.Constant) and \
                isinstance(key_node.value, str) and \
                key_node.value.startswith("SPARK_RAPIDS") and \
                key_node.value not in self.declared:
            self.diags.append(R002.diag(
                f"undeclared env key {key_node.value} read in "
                f"{self.scope}", loc=f"{self.relpath}:{lineno}"))

    def visit_Subscript(self, node):
        if self._is_environ(node.value):
            self._check_key(node.slice, node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in ("get", "pop") and \
                self._is_environ(f.value) and node.args:
            self._check_key(node.args[0], node.lineno)
        self.generic_visit(node)


def _ast_diagnostics(root: str) -> List[Diagnostic]:
    from .. import config as cfg_mod
    declared = set(getattr(cfg_mod, "DECLARED_ENV_KEYS", ()))
    diags: List[Diagnostic] = []
    for path in _py_files(root):
        relpath = os.path.relpath(path, root).replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=relpath)
        except SyntaxError as ex:
            diags.append(Diagnostic("TPU-R000", ERROR,
                                    f"unparsable module: {ex.msg}",
                                    loc=relpath))
            continue
        file_diags: List[Diagnostic] = []
        if any(relpath.startswith(h) for h in _HOT_PATHS):
            v = _HostSyncVisitor(relpath)
            v.visit(tree)
            file_diags.extend(v.diags)
            dv = _DeviceAllocVisitor(relpath)
            dv.visit(tree)
            file_diags.extend(dv.diags)
        if any(relpath.startswith(h) for h in _TIMING_PATHS):
            tv = _TimingVisitor(relpath)
            tv.visit(tree)
            file_diags.extend(tv.diags)
        if any(relpath.startswith(h) for h in _TALLY_PATHS):
            file_diags.extend(module_tally_diagnostics(tree, relpath))
        ev = _EnvReadVisitor(relpath, declared)
        ev.visit(tree)
        file_diags.extend(ev.diags)
        allowed = _allowed_lines(source) if file_diags else {}
        for d in file_diags:
            lineno = int(d.loc.rsplit(":", 1)[-1]) if ":" in d.loc else -1
            if lineno in allowed.get(d.code, ()):
                continue  # annotated sanctioned sink
            diags.append(d)
    return diags


def _registry_diagnostics() -> List[Diagnostic]:
    """TPU-R003/R004: checks against the LIVE registries, so they can
    never drift from the code the way a parallel table would."""
    import importlib
    import inspect
    import pkgutil

    diags: List[Diagnostic] = []
    from ..expr.core import Expression
    from ..plan.overrides import EXPR_RULES

    import spark_rapids_tpu.expr as expr_pkg
    for info in pkgutil.iter_modules(expr_pkg.__path__):
        mod = importlib.import_module(f"spark_rapids_tpu.expr.{info.name}")
        for name, cls in sorted(vars(mod).items()):
            if not (inspect.isclass(cls) and issubclass(cls, Expression)):
                continue
            if cls.__module__ != mod.__name__ or name.startswith("_"):
                continue
            if inspect.isabstract(cls) or cls in EXPR_RULES:
                continue
            # abstract-by-convention bases: anything further subclassed
            # within the package is a base, not a leaf operator
            if any(c is not cls and issubclass(c, cls)
                   for m2 in (vars(mod),) for c in m2.values()
                   if inspect.isclass(c)):
                continue
            diags.append(R003.diag(
                f"expression {name} has no registered TypeSig rule",
                loc=f"spark_rapids_tpu/expr/{info.name}.py"))

    from .capabilities import verify_gates
    for gate, kernel, dt in verify_gates():
        diags.append(R004.diag(
            f"gate {gate} admits {dt.name} but kernel {kernel} raises "
            f"on it", loc="spark_rapids_tpu/analysis/capabilities.py"))
    return diags


def lint_repo(root: Optional[str] = None) -> List[Diagnostic]:
    """Run every repo rule over the package source; returns ALL
    violations (baseline subtraction is the caller's concern)."""
    root = root or _package_root()
    from .diagnostics import sort_diagnostics
    from . import concurrency, determinism, hloaudit, raiseflow
    return sort_diagnostics(_ast_diagnostics(root) +
                            _registry_diagnostics() +
                            concurrency.repo_diagnostics(root) +
                            raiseflow.repo_diagnostics(root) +
                            determinism.repo_diagnostics(root) +
                            hloaudit.repo_diagnostics(root))


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------

def load_baseline(path: str) -> Set[str]:
    if not os.path.exists(path):
        return set()
    with open(path, encoding="utf-8") as f:
        return {line.rstrip("\n") for line in f
                if line.strip() and not line.startswith("#")}


def save_baseline(path: str, diags: List[Diagnostic]) -> None:
    lines = sorted({d.fingerprint() for d in diags})
    with open(path, "w", encoding="utf-8") as f:
        f.write("# tpulint repo baseline: pre-existing violations, one "
                "fingerprint per line.\n# Regenerate with: python "
                "devtools/run_lint.py --update-baseline\n")
        for line in lines:
            f.write(line + "\n")


def new_violations(diags: List[Diagnostic],
                   baseline: Set[str]) -> List[Diagnostic]:
    return [d for d in diags if d.fingerprint() not in baseline]
