"""Abstract domain for the flow-sensitive plan typechecker.

The plan lint's original rules (TPU-L001..L008) pattern-match one node
and its parent; anything that *flows* through the plan — where a
column's bytes actually live, which partitioning contract survives a
rewrite, which columns an exchange ships that nobody reads — is
invisible to them.  ``analysis/interp.py`` closes that gap with an
abstract interpreter that walks the converted ``Exec`` tree bottom-up
propagating one :class:`AbstractState` per subtree.  This module is the
domain itself:

  * **schema** — output column names, dtypes and (best-effort)
    nullability, computed *structurally* from child states + the node's
    own expressions, never by trusting the node's declared
    ``output_names``/``output_types`` (the declared schema is what
    downstream operators bound against at construction, so declared vs
    inferred drift IS the TPU-L009 hazard);
  * **residency** — whether the subtree's batches are device (jnp) or
    host (numpy) resident;
  * **distribution** — the partitioning contract (single / hash-
    clustered on keys / replicated / unknown), the lattice the
    TPU-L006/L011 contract checks evaluate in;
  * **ordering** — the within-partition sort contract;
  * **size bounds** — row estimates from the SAME model the cost-based
    optimizer uses (``plan/cost.py``'s ``estimate_rows``), widened to
    byte estimates for the L010/L012 transfer accounting.

Every element is deliberately conservative: an unknown exec degrades to
"declared schema, unknown distribution, placement residency" rather
than guessing, so the interpreter can never reject a plan on facts it
does not actually have.  The differential oracle
(``analysis/oracle.py``) keeps the optimistic parts honest: predicted
schema/residency/partitioning are asserted against real numpy-backend
execution over the golden corpus, the same discipline
``capabilities.verify_gates()`` established for dtype gates.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from .. import types as t

# residency values (match the observable fact: are the batch leaves
# jax Arrays or numpy arrays — columnar/fetch.py's batch_is_device)
DEVICE = "device"
HOST = "host"


# ---------------------------------------------------------------------------
# per-row byte widths (size-bound estimates)
# ---------------------------------------------------------------------------

_VAR_WIDTH_DEFAULT = 24  # assumed avg payload bytes for strings/binary


def dtype_width(dt: t.DataType) -> float:
    """Estimated bytes per row for one column of `dt` — flat widths are
    exact, variable-length types use the same avg-payload heuristic
    class the reference's size estimators use."""
    if isinstance(dt, (t.StringType, t.BinaryType)):
        return 4 + _VAR_WIDTH_DEFAULT          # offsets + payload
    if isinstance(dt, t.ArrayType):
        return 4 + 4 * dtype_width(dt.element_type)
    if isinstance(dt, t.MapType):
        return 4 + 4 * (dtype_width(dt.key_type) +
                        dtype_width(dt.value_type))
    if isinstance(dt, t.StructType):
        return 1 + sum(dtype_width(f.data_type) for f in dt.fields)
    if isinstance(dt, t.DecimalType):
        return 8 if dt.is64 else 16
    if isinstance(dt, (t.BooleanType, t.ByteType)):
        return 1
    if isinstance(dt, t.ShortType):
        return 2
    if isinstance(dt, (t.IntegerType, t.FloatType, t.DateType)):
        return 4
    if isinstance(dt, t.NullType):
        return 1
    return 8  # long/double/timestamp and anything else


def schema_width(dtypes: Sequence[t.DataType]) -> float:
    return sum(dtype_width(dt) for dt in dtypes)


# ---------------------------------------------------------------------------
# distribution lattice
# ---------------------------------------------------------------------------

class Dist:
    """Base partitioning fact.  ``UNKNOWN`` is the lattice top: no
    guarantee about which partition a row lives in."""

    def describe(self) -> str:
        return type(self).__name__

    def __eq__(self, other):
        return type(self) is type(other) and vars(self) == vars(other)

    def __hash__(self):
        return hash((type(self).__name__, tuple(sorted(vars(self).items()))))


class UnknownDist(Dist):
    def describe(self):
        return "unknown"


class SingleDist(Dist):
    """Exactly one partition — trivially co-locates everything."""

    def describe(self):
        return "single"


class ReplicatedDist(Dist):
    """Every consumer partition sees the WHOLE input (broadcast / the
    AQE replicate-read of a skew-split join's build side)."""

    def describe(self):
        return "replicated"


class HashDist(Dist):
    """Rows hash-routed on `keys`: equal key tuples are co-located in
    one of `num_partitions` partitions (None = count unknown, e.g.
    after AQE coalescing, which preserves clustering)."""

    def __init__(self, keys: Sequence[str],
                 num_partitions: Optional[int]):
        self.keys = tuple(keys)
        self.num_partitions = num_partitions

    def describe(self):
        n = "?" if self.num_partitions is None else self.num_partitions
        return f"hash({', '.join(self.keys)}) x {n}"


UNKNOWN = UnknownDist()
SINGLE = SingleDist()
REPLICATED = ReplicatedDist()


def clusters_on(dist: Dist, keys: Sequence[str]) -> bool:
    """True when `dist` guarantees rows with equal values of `keys` are
    co-located in one partition.  Hash distribution on a non-empty
    SUBSET of the keys suffices (equal full tuples => equal subset =>
    same partition), mirroring Spark's ClusteredDistribution check."""
    if isinstance(dist, SingleDist):
        return True
    if isinstance(dist, HashDist):
        return bool(dist.keys) and set(dist.keys) <= set(keys)
    return False


# ---------------------------------------------------------------------------
# interface requirements (what Exec.input_contracts() returns)
# ---------------------------------------------------------------------------

class Contract:
    """One declared input requirement.  ``check(states)`` receives the
    children's inferred AbstractStates and returns violation strings
    (empty = satisfied)."""

    def check(self, states: Sequence["AbstractState"]) -> List[str]:
        raise NotImplementedError


class ClusteredContract(Contract):
    """Child `child_index` must arrive hash-clustered on `keys` (or
    single-partition / replicated) — the FINAL-aggregate contract."""

    def __init__(self, keys: Sequence[str], child_index: int = 0,
                 what: str = "operator"):
        self.keys = tuple(keys)
        self.child_index = child_index
        self.what = what

    def check(self, states):
        st = states[self.child_index]
        if st.dist is None:
            return []
        if clusters_on(st.dist, self.keys) or \
                isinstance(st.dist, ReplicatedDist):
            return []
        return [f"{self.what} requires input clustered on "
                f"[{', '.join(self.keys)}] but the inferred distribution "
                f"is {st.dist.describe()}"]


class CoClusteredContract(Contract):
    """A colocated hash join's two-sided requirement: both sides
    clustered compatibly on their respective keys with the SAME
    partition count, OR the build side replicated (then the probe may be
    distributed any way), OR everything in one partition."""

    def __init__(self, left_keys: Sequence[str],
                 right_keys: Sequence[str]):
        self.left_keys = tuple(left_keys)
        self.right_keys = tuple(right_keys)

    def check(self, states):
        l, r = states[0], states[1]
        if l.dist is None or r.dist is None:
            return []
        if isinstance(r.dist, ReplicatedDist):
            return []
        if isinstance(l.dist, SingleDist) and isinstance(r.dist, SingleDist):
            return []
        if isinstance(l.dist, HashDist) and isinstance(r.dist, HashDist) \
                and clusters_on(l.dist, self.left_keys) \
                and clusters_on(r.dist, self.right_keys):
            # the two routings must agree positionally: same key position
            # prefix and same partition count (None = unknown, trust it
            # only when both sides went through the same rewrite)
            lpos = [self.left_keys.index(k) for k in l.dist.keys]
            rpos = [self.right_keys.index(k) for k in r.dist.keys]
            if lpos == rpos and l.dist.num_partitions == \
                    r.dist.num_partitions:
                return []
            return ["colocated join sides are clustered on incompatible "
                    f"routings ({l.dist.describe()} vs "
                    f"{r.dist.describe()}): matching keys can land in "
                    "different partitions"]
        return ["colocated join requires both sides clustered on the "
                f"join keys (or a replicated build side); inferred "
                f"{l.dist.describe()} / {r.dist.describe()}"]


# ---------------------------------------------------------------------------
# the per-subtree abstract state
# ---------------------------------------------------------------------------

class AbstractState:
    """Everything the interpreter knows about one subtree's output."""

    __slots__ = ("names", "dtypes", "nullable", "residency", "dist",
                 "ordering", "rows", "num_partitions", "saw_exchange")

    def __init__(self, names: Sequence[str],
                 dtypes: Sequence[t.DataType],
                 nullable: Optional[Sequence[bool]] = None,
                 residency: str = HOST,
                 dist: Optional[Dist] = None,
                 ordering: Sequence[Tuple[str, bool]] = (),
                 rows: Optional[float] = None,
                 num_partitions: Optional[int] = None,
                 saw_exchange: bool = False):
        self.names = list(names)
        self.dtypes = list(dtypes)
        self.nullable = list(nullable) if nullable is not None \
            else [True] * len(self.names)
        self.residency = residency
        self.dist = dist if dist is not None else UNKNOWN
        self.ordering = tuple(ordering)
        self.rows = rows
        self.num_partitions = num_partitions
        # whether ANY exchange exists in the subtree — the L006-vs-L011
        # discriminator (contract never established vs established then
        # broken by a rewrite)
        self.saw_exchange = saw_exchange

    # -- derived ------------------------------------------------------------
    def bytes_estimate(self) -> Optional[float]:
        if self.rows is None:
            return None
        return self.rows * schema_width(self.dtypes)

    def replace(self, **kw) -> "AbstractState":
        out = AbstractState(self.names, self.dtypes, self.nullable,
                            self.residency, self.dist, self.ordering,
                            self.rows, self.num_partitions,
                            self.saw_exchange)
        for k, v in kw.items():
            setattr(out, k, v)
        return out

    def describe(self) -> str:
        cols = ", ".join(f"{n}:{dt.name}"
                         for n, dt in zip(self.names, self.dtypes))
        rows = "?" if self.rows is None else f"~{int(self.rows)}"
        np_ = "?" if self.num_partitions is None else self.num_partitions
        ordr = ("" if not self.ordering else
                " sorted[" + ", ".join(
                    f"{n} {'ASC' if asc else 'DESC'}"
                    for n, asc in self.ordering) + "]")
        return (f"[{cols}] {self.residency} dist={self.dist.describe()} "
                f"parts={np_} rows={rows}{ordr}")


def key_names(bound_keys, child_names: Sequence[str]) -> Optional[List[str]]:
    """Map bound key expressions to child column names; None when a key
    is not a plain column reference (then no clustering fact can be
    named)."""
    from ..expr.core import AttributeReference, BoundReference
    out: List[str] = []
    for k in bound_keys:
        if isinstance(k, BoundReference):
            if 0 <= k.ordinal < len(child_names):
                out.append(child_names[k.ordinal])
            else:
                return None
        elif isinstance(k, AttributeReference):
            if k.name in child_names:
                out.append(k.name)
            else:
                return None
        else:
            return None
    return out
