"""Generated-documentation renderers.

Ref: TypeChecks.scala:1633 SupportedOpsDocs (docs/supported_ops.md) and
the RapidsConf doc printer (docs/configs.md) — both references generate
their docs from the live registries so they can never drift.  Same here:

    python -m spark_rapids_tpu.docsgen [outdir]
"""

from __future__ import annotations

import os
import sys
from typing import List

from . import config as cfg
from . import types as t


_DOC_TYPES = [
    ("BOOLEAN", t.BOOLEAN), ("BYTE", t.BYTE), ("SHORT", t.SHORT),
    ("INT", t.INT), ("LONG", t.LONG), ("FLOAT", t.FLOAT),
    ("DOUBLE", t.DOUBLE), ("DATE", t.DATE), ("TIMESTAMP", t.TIMESTAMP),
    ("STRING", t.STRING), ("DECIMAL64", t.DecimalType(18, 2)),
    ("DECIMAL128", t.DecimalType(38, 2)), ("BINARY", t.BINARY),
    ("ARRAY<INT>", t.ArrayType(t.INT)),
    ("STRUCT", t.StructType([t.StructField("f", t.INT)])),
]


def generate_supported_ops() -> str:
    """docs/supported_ops.md from the expression/exec registries."""
    from .plan.overrides import EXEC_SIGS, EXPR_RULES
    lines = [
        "# Supported Operators and Expressions",
        "",
        "Generated from the live TypeSig registries "
        "(`spark_rapids_tpu/plan/overrides.py`) — do not edit.",
        "`S` = supported on TPU, blank = falls back to CPU.",
        "",
        "## Execs", "",
        "| Exec | " + " | ".join(n for n, _ in _DOC_TYPES) + " |",
        "|" + "---|" * (len(_DOC_TYPES) + 1),
    ]
    for cls in sorted(EXEC_SIGS, key=lambda c: c.__name__):
        sig = EXEC_SIGS[cls]
        cells = ["S" if sig.is_supported(dt) else "" for _, dt in _DOC_TYPES]
        lines.append(f"| {cls.__name__} | " + " | ".join(cells) + " |")
    lines += [
        "", "## Expressions", "",
        "| Expression | " + " | ".join(n for n, _ in _DOC_TYPES) + " |",
        "|" + "---|" * (len(_DOC_TYPES) + 1),
    ]
    for cls in sorted(EXPR_RULES, key=lambda c: c.__name__):
        sig = EXPR_RULES[cls].sig
        cells = ["S" if sig.is_supported(dt) else "" for _, dt in _DOC_TYPES]
        lines.append(f"| {cls.__name__} | " + " | ".join(cells) + " |")
    return "\n".join(lines) + "\n"


def generate_lint_rules() -> str:
    """docs/lint_rules.md from the live tpulint rule catalog (the lint
    analog of supported_ops: codes/severities/docs can never drift from
    the rules actually enforced)."""
    # importing the front ends populates the catalog (interp carries the
    # flow-sensitive rules TPU-L009..L012, lifetime the tmsan memory
    # rules TPU-L013..L015, concurrency the tpucsan rules
    # TPU-R008..R010, raiseflow the tpufsan rules TPU-R011..R014,
    # determinism the tpudsan rules TPU-L016/L017 + TPU-R015/R016,
    # hloaudit the tpuxsan rules TPU-L018..L020 + TPU-R017)
    from .analysis import (concurrency, determinism,  # noqa: F401
                           hloaudit, interp, lifetime, plan_lint,
                           raiseflow, repo_lint)
    from .analysis.diagnostics import RULE_CATALOG
    lines = [
        "# tpulint rule catalog",
        "",
        "Generated from the live rule registry "
        "(`spark_rapids_tpu/analysis/`) — do not edit.  "
        "See docs/static-analysis.md for architecture and suppression.",
        "",
        "| Code | Severity | Title | Description |",
        "|---|---|---|---|",
    ]
    for code in sorted(RULE_CATALOG):
        r = RULE_CATALOG[code]
        lines.append(f"| `{r.code}` | {r.severity} | {r.title} | "
                     f"{r.doc} |")
    return "\n".join(lines) + "\n"


def generate_error_taxonomy() -> str:
    """docs/error_taxonomy.md from the tpufsan raise-graph: every typed
    engine error with its base classes, defining module and raise
    sites, plus the per-seam escape contract the fault-injection gate
    (`devtools/run_lint.py --faults`) exercises.  Generated from the
    live analysis, so the table can never drift from the code."""
    from .analysis.raiseflow import raise_graph_artifact
    art = raise_graph_artifact()
    lines = [
        "# Typed error taxonomy",
        "",
        "Generated from the tpufsan exception-flow analysis "
        "(`spark_rapids_tpu/analysis/raiseflow.py`) — do not edit.  "
        "Dump the full artifact with `tools lint --raise-graph`; "
        "`devtools/run_lint.py --faults` injects every (seam, error) "
        "pair below.",
        "",
        "## Typed errors",
        "",
        "| Error | Bases | Module | Raise sites |",
        "|---|---|---|---|",
    ]
    for name in sorted(art["taxonomy"]):
        info = art["taxonomy"][name]
        sites = ", ".join(f"`{s}`" for s in info["raise_sites"]) \
            or "(constructed by callers)"
        lines.append(
            f"| `{name}` | {', '.join(info['bases'])} | "
            f"`{info['module']}` | {sites} |")
    lines += [
        "",
        "## Public seams",
        "",
        "Per seam: the typed errors that can escape to its caller "
        "(the injection campaign's reach set) and any untyped "
        "operational leaks (must be empty — TPU-R013).",
        "",
        "| Seam | Function | Typed errors | Untyped leaks |",
        "|---|---|---|---|",
    ]
    for label in sorted(art["seams"]):
        s = art["seams"][label]
        typed = ", ".join(f"`{e}`" for e in s["typed"]) or "—"
        leaks = ", ".join(f"`{e}`" for e in s["untyped"]) or "—"
        lines.append(f"| {label} | `{s['fid']}` | {typed} | {leaks} |")
    lines += [
        "",
        f"Planned injections: {len(art['injections'])} "
        f"(seam × typed-error pairs).",
    ]
    return "\n".join(lines) + "\n"


def write_docs(outdir: str = "docs") -> List[str]:
    os.makedirs(outdir, exist_ok=True)
    paths = []
    p = os.path.join(outdir, "configs.md")
    with open(p, "w") as f:
        f.write(cfg.generate_docs())
    paths.append(p)
    p = os.path.join(outdir, "supported_ops.md")
    with open(p, "w") as f:
        f.write(generate_supported_ops())
    paths.append(p)
    p = os.path.join(outdir, "lint_rules.md")
    with open(p, "w") as f:
        f.write(generate_lint_rules())
    paths.append(p)
    p = os.path.join(outdir, "error_taxonomy.md")
    with open(p, "w") as f:
        f.write(generate_error_taxonomy())
    paths.append(p)
    return paths


if __name__ == "__main__":
    outdir = sys.argv[1] if len(sys.argv) > 1 else "docs"
    for p in write_docs(outdir):
        print(p)
